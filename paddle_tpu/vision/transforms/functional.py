"""Functional image ops on numpy HWC arrays (reference:
vision/transforms/functional*.py — the cv2/PIL backends collapse to one
numpy backend here; PIL images are accepted and converted)."""
from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np
from ...core import enforce as E

__all__ = ["to_tensor", "normalize", "resize", "pad", "crop", "center_crop",
           "hflip", "vflip", "rotate", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue",
           "to_grayscale"]


def _as_np(img):
    if hasattr(img, "convert"):   # PIL
        img = np.asarray(img)
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    """HWC -> float32 tensor (CHW default). Integer dtypes scale to [0,1]
    by 255 (dtype-based, like the reference); float inputs pass through."""
    arr = _as_np(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    scale = np.issubdtype(arr.dtype, np.integer)
    arr = arr.astype(np.float32)
    if scale:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    import paddle_tpu as P
    return P.to_tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (arr - mean) / std


def resize(img, size, interpolation="bilinear"):
    """size: int (short side) or (h, w). Bilinear on numpy."""
    arr = _as_np(img).astype(np.float32)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(w * size / h)
        else:
            nh, nw = int(h * size / w), size
    else:
        nh, nw = size
    ys = np.clip((np.arange(nh) + 0.5) * h / nh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(nw) + 0.5) * w / nw - 0.5, 0, w - 1)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    out = (arr[y0][:, x0] * (1 - wy) * (1 - wx)
           + arr[y0][:, x1] * (1 - wy) * wx
           + arr[y1][:, x0] * wy * (1 - wx)
           + arr[y1][:, x1] * wy * wx)
    if squeeze:
        out = out[:, :, 0]
    return out


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _as_np(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    width = [(top, bottom), (left, right)] + \
        [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, width, mode=mode, **kw)


def crop(img, top, left, height, width):
    return _as_np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _as_np(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _as_np(img)[:, ::-1]


def vflip(img):
    return _as_np(img)[::-1]


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotation about the center; ``expand=True`` grows the canvas to hold
    the whole rotated image; nearest or bilinear sampling."""
    arr = _as_np(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else center
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        # epsilon guards against float error inflating exact multiples
        nh = int(np.ceil(abs(h * cos) + abs(w * sin) - 1e-9))
        nw = int(np.ceil(abs(w * cos) + abs(h * sin) - 1e-9))
        ocy, ocx = (nh - 1) / 2, (nw - 1) / 2
    else:
        nh, nw = h, w
        ocy, ocx = cy, cx
    yy, xx = np.mgrid[0:nh, 0:nw]
    # inverse-map each output pixel to source coordinates
    ys = cos * (yy - ocy) + sin * (xx - ocx) + cy
    xs = -sin * (yy - ocy) + cos * (xx - ocx) + cx
    out = _inverse_sample(arr, ys, xs, interpolation, fill)
    if squeeze:
        out = out[:, :, 0]
    return out


def adjust_brightness(img, factor):
    arr = _as_np(img).astype(np.float32) * factor
    return np.clip(arr, 0, 255 if arr.max() > 1 else 1.0)


def adjust_contrast(img, factor):
    arr = _as_np(img).astype(np.float32)
    mean = arr.mean()
    out = mean + factor * (arr - mean)
    return np.clip(out, 0, 255 if arr.max() > 1 else 1.0)


def adjust_saturation(img, factor):
    """Blend with the grayscale image: factor 0 = grayscale, 1 = original."""
    arr = _as_np(img).astype(np.float32)
    gray = to_grayscale(arr, num_output_channels=3) if arr.ndim == 3 \
        else arr
    out = gray + factor * (arr - gray)
    return np.clip(out, 0, 255 if arr.max() > 1 else 1.0)


def adjust_hue(img, hue_factor):
    """Shift hue by ``hue_factor`` (in [-0.5, 0.5]) via HSV conversion."""
    if not -0.5 <= hue_factor <= 0.5:
        raise E.InvalidArgumentError("hue_factor must be in [-0.5, 0.5]")
    arr = _as_np(img).astype(np.float32)
    high = arr.max() > 1
    x = arr / 255.0 if high else arr
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = x.max(-1)
    mn = x.min(-1)
    d = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = (((g - b) / d) % 6)[m]
    m = mx == g
    h[m] = ((b - r) / d + 2)[m]
    m = mx == b
    h[m] = ((r - g) / d + 4)[m]
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, d / (mx + 1e-12), 0.0)
    v = mx
    # HSV -> RGB
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    out = np.zeros_like(x)
    for idx, (rr, gg, bb) in enumerate(
            [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v),
             (v, p, q)]):
        m = i == idx
        out[..., 0][m] = rr[m]
        out[..., 1][m] = gg[m]
        out[..., 2][m] = bb[m]
    return out * 255.0 if high else out


def to_grayscale(img, num_output_channels=1):
    arr = _as_np(img).astype(np.float32)
    gray = arr @ np.array([0.299, 0.587, 0.114], np.float32) \
        if arr.ndim == 3 else arr
    if num_output_channels == 3:
        gray = np.stack([gray] * 3, axis=-1)
    elif arr.ndim == 3:
        gray = gray[..., None]
    return gray


def _inverse_sample(arr, ys, xs, interpolation, fill):
    """Sample arr (HWC) at float source coords (ys, xs) with
    nearest/bilinear, fill outside."""
    h, w = arr.shape[:2]
    nh, nw = ys.shape
    out = np.full((nh, nw, arr.shape[2]), fill, dtype=arr.dtype)
    if interpolation == "bilinear":
        # validity by the real coordinate (inclusive of the last row/col);
        # the interpolation corners clip to h-2/w-2 so ys==h-1 reads the
        # last row with weight 1
        valid = (ys >= 0) & (ys <= h - 1) & (xs >= 0) & (xs <= w - 1)
        y0c = np.clip(np.floor(ys).astype(int), 0, h - 2)
        x0c = np.clip(np.floor(xs).astype(int), 0, w - 2)
        wy = np.clip(ys - y0c, 0.0, 1.0)[..., None]
        wx = np.clip(xs - x0c, 0.0, 1.0)[..., None]
        interp = (arr[y0c, x0c] * (1 - wy) * (1 - wx)
                  + arr[y0c, x0c + 1] * (1 - wy) * wx
                  + arr[y0c + 1, x0c] * wy * (1 - wx)
                  + arr[y0c + 1, x0c + 1] * wy * wx)
        out[valid] = interp[valid].astype(arr.dtype)
    else:
        yi = np.round(ys).astype(int)
        xi = np.round(xs).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out[valid] = arr[yi[valid], xi[valid]]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """2D affine: rotate(angle) @ shear @ scale, then translate
    (reference: transforms/functional.py affine — same parameterization
    as torchvision). Host-side inverse mapping."""
    arr = _as_np(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else \
        (center[1], center[0])
    if np.isscalar(shear):
        shear = (float(shear), 0.0)
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward matrix (x right, y down): T * C * R * Sh * Sc * C^-1
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = scale * np.array([[a, b], [c, d]])
    # inverse map: src = M^-1 (dst - center - translate) + center
    minv = np.linalg.inv(m)
    yy, xx = np.mgrid[0:h, 0:w]
    dx = xx - cx - translate[0]
    dy = yy - cy - translate[1]
    xs = minv[0, 0] * dx + minv[0, 1] * dy + cx
    ys = minv[1, 0] * dx + minv[1, 1] * dy + cy
    out = _inverse_sample(arr, ys, xs, interpolation, fill)
    return out[:, :, 0] if squeeze else out


def _perspective_coeffs(startpoints, endpoints):
    """Solve the 8-dof homography mapping endpoints -> startpoints."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.extend([sx, sy])
    coeffs = np.linalg.lstsq(np.asarray(a, np.float64),
                             np.asarray(b, np.float64), rcond=None)[0]
    return coeffs


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp given 4 source and 4 destination corner points
    (reference: transforms/functional.py perspective)."""
    arr = _as_np(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    co = _perspective_coeffs(startpoints, endpoints)
    yy, xx = np.mgrid[0:h, 0:w]
    denom = co[6] * xx + co[7] * yy + 1.0
    xs = (co[0] * xx + co[1] * yy + co[2]) / denom
    ys = (co[3] * xx + co[4] * yy + co[5]) / denom
    out = _inverse_sample(arr, ys, xs, interpolation, fill)
    return out[:, :, 0] if squeeze else out


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the rectangle [i:i+h, j:j+w] with value ``v`` (reference:
    transforms/functional.py erase). Works on HWC numpy or Tensor CHW."""
    from ...core.tensor import Tensor

    if isinstance(img, Tensor):
        import jax.numpy as jnp

        arr = img._data
        val = jnp.broadcast_to(jnp.asarray(v, arr.dtype),
                               arr.shape[:-2] + (h, w))
        new = arr.at[..., i:i + h, j:j + w].set(val)
        if inplace:
            img._data = new
            return img
        return Tensor(new)
    arr = _as_np(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = v
    return out
