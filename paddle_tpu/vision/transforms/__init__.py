"""paddle.vision.transforms parity.

Reference: python/paddle/vision/transforms/ (transforms.py + functional).
TPU-native notes: transforms run host-side on numpy HWC images in the
DataLoader workers (same stage as the reference's CPU transforms); the
device never sees per-sample python work."""
from .transforms import (BaseTransform, BrightnessTransform, CenterCrop,  # noqa
                         ColorJitter, Compose, ContrastTransform, Normalize,
                         Pad, RandomCrop, RandomHorizontalFlip,
                         RandomResizedCrop, RandomRotation, RandomVerticalFlip,
                         Resize, ToTensor, Transpose)
from . import functional  # noqa
