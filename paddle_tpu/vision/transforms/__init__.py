"""paddle.vision.transforms parity.

Reference: python/paddle/vision/transforms/ (transforms.py + functional).
TPU-native notes: transforms run host-side on numpy HWC images in the
DataLoader workers (same stage as the reference's CPU transforms); the
device never sees per-sample python work."""
from .transforms import (BaseTransform, BrightnessTransform, CenterCrop,  # noqa
                         ColorJitter, Compose, ContrastTransform, Grayscale,
                         HueTransform, Normalize, Pad, RandomAffine,
                         RandomCrop, RandomErasing, RandomHorizontalFlip,
                         RandomPerspective, RandomResizedCrop, RandomRotation,
                         RandomVerticalFlip, Resize, SaturationTransform,
                         ToTensor, Transpose)
from .functional import (adjust_brightness, adjust_contrast, adjust_hue,  # noqa
                         adjust_saturation, affine, center_crop, crop,
                         erase, hflip, normalize, pad, perspective, resize,
                         rotate, to_grayscale, to_tensor, vflip)
from . import functional  # noqa
