"""Math op long tail (paddle.tensor math/special-function parity).

Reference capability: python/paddle/tensor/math.py + the phi special-math
kernels (i0/i1/polygamma/gammainc — paddle/phi/kernels/cpu/*_kernel.cc).
TPU-native: everything is a jnp/lax one-liner compiled by XLA; special
functions come from jax.scipy.special (native TPU lowerings), not bound
C libraries.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ._op import op_fn, unwrap, wrap
from ..core import enforce as E

__all__ = [
    "copysign", "nextafter", "i0", "i0e", "i1", "i1e", "sinc", "gammaln",
    "gammainc", "gammaincc", "multigammaln", "logcumsumexp", "cummin",
    "cummax", "nanmedian", "nanquantile", "neg", "sgn", "signbit",
    "bitwise_left_shift", "bitwise_right_shift", "bucketize", "diff",
    "cumulative_trapezoid", "frexp", "floor_mod", "remainder", "renorm",
    "multiplex", "polar", "reduce_as", "take", "isneginf", "isposinf",
    "isreal", "is_complex", "is_floating_point", "is_integer", "rank",
    "increment", "add_n", "broadcast_shape",
]


@op_fn
def copysign(x, y):
    return jnp.copysign(x, y)


@op_fn(differentiable=False)
def nextafter(x, y):
    return jnp.nextafter(x, y)


@op_fn
def i0(x):
    return jsp.i0(x)


@op_fn
def i0e(x):
    return jsp.i0e(x)


@op_fn
def i1(x):
    return jsp.i1(x)


@op_fn
def i1e(x):
    return jsp.i1e(x)


@op_fn
def sinc(x):
    return jnp.sinc(x)


@op_fn
def gammaln(x):
    return jsp.gammaln(x)


@op_fn
def gammainc(x, y):
    return jsp.gammainc(x, y)


@op_fn
def gammaincc(x, y):
    return jsp.gammaincc(x, y)


@op_fn(name="multigammaln_op")
def _multigammaln(x, *, p=1):
    return jsp.multigammaln(x, p)


def multigammaln(x, p=1, name=None):
    return _multigammaln(x, p=int(p))


@op_fn(name="logcumsumexp")
def _logcumsumexp(x, *, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    out = _logcumsumexp(x, axis=axis)
    if dtype is not None:
        out = out.astype(dtype)
    return out


@op_fn(name="cummin_op")
def _cummin(x, *, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummin(x, axis=axis)
    n = x.shape[axis]
    iota = jax.lax.broadcasted_iota(jnp.int64, x.shape, axis)
    hit = x == jax.lax.cummin(x, axis=axis)
    idx = jnp.where(hit, iota, -1)
    idx = jax.lax.cummax(idx, axis=axis)
    return vals, idx


def cummin(x, axis=None, dtype="int64", name=None):
    vals, idx = _cummin(x, axis=axis)
    return vals, idx.astype(dtype) if dtype else idx


@op_fn(name="cummax_op")
def _cummax_full(x, *, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummax(x, axis=axis)
    iota = jax.lax.broadcasted_iota(jnp.int64, x.shape, axis)
    hit = x == vals
    idx = jnp.where(hit, iota, -1)
    idx = jax.lax.cummax(idx, axis=axis)
    return vals, idx


def cummax(x, axis=None, dtype="int64", name=None):
    vals, idx = _cummax_full(x, axis=axis)
    return vals, idx.astype(dtype) if dtype else idx


@op_fn(name="nanmedian_op")
def _nanmedian(x, *, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _nanmedian(x, axis=axis, keepdim=keepdim)


@op_fn(name="nanquantile_op")
def _nanquantile(x, *, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.nanquantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim,
                           method=interpolation)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _nanquantile(x, q=q, axis=axis, keepdim=keepdim,
                        interpolation=interpolation)


@op_fn
def neg(x):
    return -x


@op_fn
def sgn(x):
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0.0 + 0.0j, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


@op_fn(differentiable=False)
def signbit(x):
    return jnp.signbit(x)


@op_fn(differentiable=False)
def bitwise_left_shift(x, y, *, is_arithmetic=True):
    return jnp.left_shift(x, y)


@op_fn(differentiable=False)
def bitwise_right_shift(x, y, *, is_arithmetic=True):
    return (jnp.right_shift(x, y) if is_arithmetic
            else jax.lax.shift_right_logical(x, y))


@op_fn(differentiable=False, name="bucketize_op")
def _bucketize(x, sorted_sequence, *, out_int32=False, right=False):
    side = "right" if right else "left"
    idx = jnp.searchsorted(sorted_sequence, x, side=side)
    return idx.astype(jnp.int32) if out_int32 else idx.astype(jnp.int64)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return _bucketize(x, sorted_sequence, out_int32=out_int32, right=right)


@op_fn(name="diff_op")
def _diff(x, *, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return _diff(x, n=n, axis=axis,
                 prepend=unwrap(prepend) if prepend is not None else None,
                 append=unwrap(append) if append is not None else None)


@op_fn(name="cumulative_trapezoid_op")
def _cumulative_trapezoid(y, *, x=None, dx=None, axis=-1):
    n = y.shape[axis]
    y0 = jax.lax.slice_in_dim(y, 0, n - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(y, 1, n, axis=axis)
    if x is not None:
        x0 = jax.lax.slice_in_dim(x, 0, x.shape[axis] - 1, axis=axis)
        x1 = jax.lax.slice_in_dim(x, 1, x.shape[axis], axis=axis)
        d = x1 - x0
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum((y0 + y1) * d / 2.0, axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    return _cumulative_trapezoid(
        y, x=unwrap(x) if x is not None else None, dx=dx, axis=axis)


def frexp(x, name=None):
    m, e = jnp.frexp(unwrap(x))
    return wrap(m), wrap(e.astype(jnp.int32))


@op_fn
def floor_mod(x, y):
    return jnp.mod(x, y)


def remainder(x, y, name=None):
    from .math import mod
    return mod(x, y)


@op_fn(name="renorm_op")
def _renorm(x, *, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    factor = jnp.where(norms > max_norm,
                       max_norm / (norms + 1e-7), 1.0)
    flat = flat * factor[:, None]
    return jnp.moveaxis(flat.reshape(moved.shape), 0, axis)


def renorm(x, p, axis, max_norm, name=None):
    return _renorm(x, p=float(p), axis=int(axis), max_norm=float(max_norm))


@op_fn(name="multiplex_op")
def _multiplex(*inputs, index):
    stacked = jnp.stack(inputs, axis=0)     # [n, batch, ...]
    sel = index.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[sel, rows]


def multiplex(inputs, index, name=None):
    return _multiplex(*[unwrap(i) for i in inputs], index=unwrap(index))


@op_fn
def polar(abs, angle):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


@op_fn(name="reduce_as_op")
def _reduce_as(x, *, target_shape):
    # sum x down to target_shape (reference: tensor/math.py reduce_as)
    ndiff = x.ndim - len(target_shape)
    axes = list(range(ndiff))
    for i, (xs, ts) in enumerate(zip(x.shape[ndiff:], target_shape)):
        if ts == 1 and xs != 1:
            axes.append(ndiff + i)
    out = jnp.sum(x, axis=tuple(axes), keepdims=False) if axes else x
    return out.reshape(target_shape)


def reduce_as(x, target, name=None):
    return _reduce_as(x, target_shape=tuple(unwrap(target).shape))


@op_fn(name="take_op")
def _take(x, index, *, mode="raise"):
    flat = x.reshape(-1)
    idx = index.astype(jnp.int64)
    n = flat.shape[0]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    else:   # 'raise': negative wraps once (paddle semantics under jit)
        idx = jnp.where(idx < 0, idx + n, idx)
        idx = jnp.clip(idx, 0, n - 1)
    return flat[idx]


def take(x, index, mode="raise", name=None):
    if mode not in ("raise", "wrap", "clip"):
        raise E.InvalidArgumentError(f"'mode' must be raise/wrap/clip, got {mode}")
    return _take(x, index, mode=mode)


@op_fn(differentiable=False)
def isneginf(x):
    return jnp.isneginf(x)


@op_fn(differentiable=False)
def isposinf(x):
    return jnp.isposinf(x)


@op_fn(differentiable=False)
def isreal(x):
    return jnp.isreal(x)


def is_complex(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.integer)


def rank(input):
    return wrap(jnp.asarray(unwrap(input).ndim, jnp.int32))


def increment(x, value=1.0, name=None):
    """In-place increment (reference: tensor/math.py increment — mutation
    is rebinding on the Tensor facade)."""
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x._data = x._data + value
        return x
    return wrap(x + value)


@op_fn(name="add_n_op")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    return _add_n(*inputs)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))
