"""Composite-op registration: surface ops implemented as compositions of
primitive ops into the kernel registry.

Reference capability: the op registry in the reference spans both
primitive kernels (phi/kernels) and composite/codegen'd API ops
(paddle/phi/api/yaml/ops.yaml + generated composites). Here primitives
register via @op_fn; this module registers the composition-implemented
surface (creation, manipulation-by-composition, inplace families,
random fills) so the dispatch registry reflects the full op surface the
way the reference's OpInfoMap does. Each entry dispatches to the live
eager implementation — kernels/__init__.py fallbacks and trace counters
see them like any other op.
"""
from __future__ import annotations

from ._op import _OP_REGISTRY

# Names whose implementation is a composition over registered primitives
# (or a creation/random routine). Grouped as the reference yaml groups
# its op defs.
_COMPOSITE_NAMES = [
    # creation
    "arange", "empty", "empty_like", "eye", "full", "assign",
    "create_tensor", "diag_embed", "meshgrid", "tril_indices",
    "triu_indices",
    # random
    "bernoulli", "binomial", "gumbel", "standard_gamma", "randint_like",
    # manipulation compositions
    "atleast_1d", "atleast_2d", "atleast_3d", "broadcast_tensors",
    "chunk", "column_stack", "dstack", "hstack", "vstack", "row_stack",
    "dsplit", "hsplit", "vsplit", "expand_as", "as_strided",
    "diagonal_scatter", "crop", "moveaxis", "rot90", "select_scatter",
    "slice_scatter", "view", "view_as", "unflatten",
    # math compositions
    "addmm", "allclose", "bmm", "cdist", "complex", "corrcoef", "cov",
    "cummax", "cummin", "cumulative_trapezoid", "diff", "dist",
    "equal_all", "frexp", "histogram", "histogramdd", "hypot",
    "increment", "inner", "outer", "kron", "lerp", "logaddexp",
    "log_normal", "lstsq", "lu", "lu_unpack", "matrix_power", "median",
    "nanmean", "nanmedian", "nansum", "nanquantile", "pdist", "polar",
    "quantile", "trapezoid", "vander", "combinations", "logspace",
    "multi_dot", "slogdet", "histogram_bin_edges",
    # indexing / search compositions
    "index_fill", "index_put", "index_sample", "index_select",
    "masked_select", "mode", "searchsorted", "take_along_axis",
    "put_along_axis", "top_p_sampling", "unique_consecutive",
    # linalg surface
    "cholesky_solve", "eigh", "eigvalsh", "householder_product",
    "matrix_rank", "ormqr", "pinv", "triangular_solve",
]


def register_composites():
    """Install every present composite into the op registry (idempotent;
    names already claimed by an @op_fn primitive are left alone)."""
    import paddle_tpu as _paddle

    added = 0
    for name in _COMPOSITE_NAMES:
        if name in _OP_REGISTRY:
            continue
        fn = getattr(_paddle, name, None)
        if fn is None or not callable(fn):
            continue
        if not hasattr(fn, "op_name"):    # aliases keep their first name
            fn.op_name = name
        _OP_REGISTRY[name] = fn
        added += 1

    # inplace family: every registered x_ over a registered base
    for name in list(vars(_paddle)):
        if name.endswith("_") and not name.startswith("_"):
            fn = getattr(_paddle, name)
            if callable(fn) and not isinstance(fn, type) \
                    and name not in _OP_REGISTRY:
                if not hasattr(fn, "op_name"):
                    fn.op_name = name
                _OP_REGISTRY[name] = fn
                added += 1
    return added
