"""Linear-algebra decompositions and statistics ops.

Reference capability: python/paddle/tensor/linalg.py (svd/qr/eig/lu/... —
backed by phi LAPACK kernels, paddle/phi/kernels/cpu/svd_kernel.cc etc.).
TPU-native: everything lowers through jnp.linalg / lax.linalg, which XLA
compiles natively on TPU where supported (svd, qr, eigh, cholesky, lu)
and via CPU callback semantics for the general complex eig family —
matching the reference, whose eig is CPU-only too (eig_kernel.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._op import op_fn, unwrap, wrap

__all__ = [
    "svd", "svd_lowrank", "pca_lowrank", "qr", "eig", "eigvals", "eigh",
    "eigvalsh", "lu", "lu_unpack", "householder_product", "ormqr", "cond",
    "cov", "corrcoef", "cdist", "dist", "mv", "inverse", "lstsq", "vander",
    "histogram", "histogramdd", "vector_norm", "matrix_transpose", "addmm",
]


@op_fn(name="svd")
def _svd(x, *, full_matrices=False):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


def svd(x, full_matrices=False, name=None):
    return _svd(x, full_matrices=full_matrices)


@op_fn(name="qr_op")
def _qr(x, *, mode="reduced"):
    if mode == "r":
        return (jnp.linalg.qr(x, mode="r"),)
    return tuple(jnp.linalg.qr(x, mode=mode))


def qr(x, mode="reduced", name=None):
    out = _qr(x, mode=mode)
    return out[0] if mode == "r" else out


@op_fn(differentiable=False)
def eig(x):
    w, v = jnp.linalg.eig(x)
    return w, v


@op_fn(differentiable=False, name="eigvals")
def _eigvals(x):
    return jnp.linalg.eigvals(x)


def eigvals(x, name=None):
    return _eigvals(x)


@op_fn(name="eigh_op")
def _eigh(x, *, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


def eigh(x, UPLO="L", name=None):
    return _eigh(x, UPLO=UPLO)


@op_fn(name="eigvalsh_op")
def _eigvalsh(x, *, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return _eigvalsh(x, UPLO=UPLO)


@op_fn(name="lu_op")
def _lu(x):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, piv.astype(jnp.int32) + 1   # paddle pivots are 1-based


def lu(x, pivot=True, get_infos=False, name=None):
    lu_mat, piv = _lu(x)
    if get_infos:
        info = wrap(jnp.zeros(x.shape[:-2], jnp.int32))
        return lu_mat, piv, info
    return lu_mat, piv


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack LU factorization into P, L, U (reference:
    python/paddle/tensor/linalg.py lu_unpack)."""
    xa, piv = unwrap(x), unwrap(y)
    m, n = xa.shape[-2], xa.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(xa[..., :, :k], -1) + jnp.eye(m, k, dtype=xa.dtype)
        U = jnp.triu(xa[..., :k, :])
    if unpack_pivots:
        def perm_from_piv(p):
            perm = jnp.arange(m)
            def body(i, perm):
                j = p[i] - 1
                pi, pj = perm[i], perm[j]
                perm = perm.at[i].set(pj)
                return perm.at[j].set(pi)
            return jax.lax.fori_loop(0, p.shape[0], body, perm)
        flat_piv = piv.reshape((-1, piv.shape[-1]))
        perms = jax.vmap(perm_from_piv)(flat_piv)
        perms = perms.reshape(piv.shape[:-1] + (m,))
        P = jax.nn.one_hot(perms, m, dtype=xa.dtype)
        P = jnp.swapaxes(P, -1, -2)
    return wrap(P), wrap(L), wrap(U)


@op_fn(name="householder_product_op")
def _householder_product(x, tau):
    # out = H_0 H_1 ... H_{k-1} [:, :n], H_i = I - tau_i v_i v_i^T
    m, n = x.shape[-2], x.shape[-1]

    def one(mat, t):
        q = jnp.eye(m, dtype=mat.dtype)
        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0,
                          jnp.where(jnp.arange(m) == i, 1.0, mat[:, i]))
            h = jnp.eye(m, dtype=mat.dtype) - t[i] * jnp.outer(v, v)
            return q @ h
        q = jax.lax.fori_loop(0, t.shape[0], body, q)
        return q[:, :n]

    if x.ndim == 2:
        return one(x, tau)
    batch = x.shape[:-2]
    xf = x.reshape((-1,) + x.shape[-2:])
    tf = tau.reshape((-1, tau.shape[-1]))
    return jax.vmap(one)(xf, tf).reshape(batch + (m, n))


def householder_product(x, tau, name=None):
    return _householder_product(x, tau)


@op_fn(name="ormqr_op")
def _ormqr(x, tau, other, *, left=True, transpose=False):
    # apply the k Householder reflectors H_i = I - tau_i v_i v_i^T to
    # `other` directly (the LAPACK ormqr strategy — no explicit Q);
    # batched inputs vmap a 2-D kernel, like _householder_product
    m = x.shape[-2]
    k = tau.shape[-1]
    # left, no transpose: Q C = H_0 ... H_{k-1} C  (apply right-to-left)
    # left, transpose:    Q^T C = H_{k-1} ... H_0 C
    # right, no transpose: C Q = C H_0 ... H_{k-1} (apply left-to-right)
    reverse = (left and not transpose) or (not left and transpose)

    def one(mat, t, c0):
        order = jnp.arange(k)[::-1] if reverse else jnp.arange(k)

        def body(j, c):
            i = order[j]
            col = jax.lax.dynamic_index_in_dim(mat, i, 1, keepdims=False)
            v = jnp.where(jnp.arange(m) < i, 0.0,
                          jnp.where(jnp.arange(m) == i, 1.0, col))
            ti = t[i]
            if left:
                vc = v @ c                   # [n]
                return c - ti * v[:, None] * vc[None, :]
            cv = c @ v                       # [rows]
            return c - ti * cv[:, None] * v[None, :]

        return jax.lax.fori_loop(0, k, body, c0)

    if x.ndim == 2:
        return one(x, tau, other)
    batch = x.shape[:-2]
    xf = x.reshape((-1,) + x.shape[-2:])
    tf = tau.reshape((-1, k))
    cf = other.reshape((-1,) + other.shape[-2:])
    return jax.vmap(one)(xf, tf, cf).reshape(batch + other.shape[-2:])


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by Q (implicit in Householder form) from a QR
    (reference: tensor/linalg.py ormqr)."""
    return _ormqr(x, tau, other, left=left, transpose=transpose)


@op_fn(name="cond_op", differentiable=False)
def _cond(x, *, p=None):
    p = 2 if p is None else p
    if p in (2, -2):
        s = jnp.linalg.svd(x, compute_uv=False)
        return (s[..., 0] / s[..., -1]) if p == 2 else (s[..., -1] / s[..., 0])
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return _cond(x, p=p)


@op_fn(name="cov_op")
def _cov(x, *, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return _cov(x, rowvar=rowvar, ddof=ddof,
                fweights=unwrap(fweights) if fweights is not None else None,
                aweights=unwrap(aweights) if aweights is not None else None)


@op_fn(name="corrcoef_op")
def _corrcoef(x, *, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def corrcoef(x, rowvar=True, name=None):
    return _corrcoef(x, rowvar=rowvar)


@op_fn(name="cdist_op")
def _cdist(x, y, *, p=2.0):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    if p == 0:
        return jnp.sum((diff != 0).astype(x.dtype), axis=-1)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    return _cdist(x, y, p=float(p))


@op_fn(name="dist_op")
def _dist(x, y, *, p=2.0):
    d = jnp.abs(x - y)
    if p == float("inf"):
        return jnp.max(d)
    if p == float("-inf"):
        return jnp.min(d)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype))
    return jnp.sum(d ** p) ** (1.0 / p)


def dist(x, y, p=2.0, name=None):
    return _dist(x, y, p=float(p))


@op_fn
def mv(x, vec):
    return jnp.matmul(x, vec)


def inverse(x, name=None):
    from .linalg import inv
    return inv(x)


@op_fn(name="lstsq_op")
def _lstsq_full(x, y, *, rcond=None):
    sol, res, rank_, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank_.astype(jnp.int32), sv


def lstsq(x, y, rcond=None, driver=None, name=None):
    return _lstsq_full(x, y, rcond=rcond)


@op_fn(name="vander_op")
def _vander(x, *, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def vander(x, n=None, increasing=False, name=None):
    return _vander(x, n=n, increasing=increasing)


@op_fn(name="histogram_op", differentiable=False)
def _histogram(x, *, bins=100, min=0, max=0, weight=None, density=False):
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo, hi = jnp.min(x), jnp.max(x)
        hi = jnp.where(hi == lo, lo + 1.0, hi)
    hist, _ = jnp.histogram(x.reshape(-1),
                            bins=bins, range=(lo, hi),
                            weights=None if weight is None
                            else weight.reshape(-1),
                            density=density)
    if density or weight is not None:
        return hist
    return hist.astype(jnp.int64)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    return _histogram(input, bins=bins, min=min, max=max,
                      weight=unwrap(weight) if weight is not None else None,
                      density=density)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    xa = unwrap(x)
    h, edges = jnp.histogramdd(xa, bins=bins, range=ranges, density=density,
                               weights=unwrap(weights)
                               if weights is not None else None)
    return wrap(h), [wrap(e) for e in edges]


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference: tensor/linalg.py svd_lowrank,
    Halko et al. subspace iteration — deterministic start vectors here so
    the op is jit-stable)."""
    xa = unwrap(x)
    if M is not None:
        xa = xa - unwrap(M)
    m, n = xa.shape[-2], xa.shape[-1]
    q = min(q, m, n)
    key = jax.random.key(0)
    omega = jax.random.normal(key, xa.shape[:-2] + (n, q), xa.dtype)
    y = xa @ omega
    Q, _ = jnp.linalg.qr(y)
    for _ in range(niter):
        # re-orthonormalize each power iteration (numerical stability —
        # plain power iteration collapses the basis in float32)
        Z, _ = jnp.linalg.qr(jnp.swapaxes(xa, -1, -2) @ Q)
        Q, _ = jnp.linalg.qr(xa @ Z)
    b = jnp.swapaxes(Q, -1, -2) @ xa
    u, s, vh = jnp.linalg.svd(b, full_matrices=False)
    return wrap(Q @ u), wrap(s), wrap(jnp.swapaxes(vh, -1, -2))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    xa = unwrap(x)
    m, n = xa.shape[-2], xa.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        xa = xa - jnp.mean(xa, axis=-2, keepdims=True)
    u, s, v = svd_lowrank(wrap(xa), q=q, niter=niter)
    return u, s, v


@op_fn(name="vector_norm_op")
def _vector_norm(x, *, p=2.0, axis=None, keepdim=False):
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    if axis is not None and isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _vector_norm(x, p=float(p), axis=axis, keepdim=keepdim)


@op_fn
def matrix_transpose(x):
    return jnp.swapaxes(x, -1, -2)


@op_fn(name="addmm_op")
def _addmm(input, x, y, *, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _addmm(input, x, y, beta=beta, alpha=alpha)
