"""Comparison / logical / bitwise ops (paddle.tensor.logic parity)."""
from __future__ import annotations

import jax.numpy as jnp

from ._op import op_fn, unwrap


@op_fn(differentiable=False)
def equal(x, y):
    return jnp.equal(x, y)


@op_fn(differentiable=False)
def not_equal(x, y):
    return jnp.not_equal(x, y)


@op_fn(differentiable=False)
def less_than(x, y):
    return jnp.less(x, y)


@op_fn(differentiable=False)
def less_equal(x, y):
    return jnp.less_equal(x, y)


@op_fn(differentiable=False)
def greater_than(x, y):
    return jnp.greater(x, y)


@op_fn(differentiable=False)
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@op_fn(differentiable=False)
def logical_and(x, y):
    return jnp.logical_and(x, y)


@op_fn(differentiable=False)
def logical_or(x, y):
    return jnp.logical_or(x, y)


@op_fn(differentiable=False)
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@op_fn(differentiable=False)
def logical_not(x):
    return jnp.logical_not(x)


@op_fn(differentiable=False)
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@op_fn(differentiable=False)
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@op_fn(differentiable=False)
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@op_fn(differentiable=False)
def bitwise_not(x):
    return jnp.bitwise_not(x)


@op_fn(differentiable=False)
def isclose(x, y, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    from ._op import wrap
    return wrap(jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol,
                             equal_nan=equal_nan))


def equal_all(x, y):
    from ._op import wrap
    return wrap(jnp.array_equal(unwrap(x), unwrap(y)))


def is_tensor(x):
    from ..core.tensor import Tensor
    return isinstance(x, Tensor)


@op_fn(differentiable=False)
def isin(x, test_x):
    return jnp.isin(x, test_x)
