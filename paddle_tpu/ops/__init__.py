"""Aggregate op namespace (the `_C_ops`-equivalent flat op surface,
reference: python/paddle/_C_ops.py re-exporting core.eager.ops). Also attaches
the op set onto Tensor as methods (paddle Tensor method parity)."""
from __future__ import annotations

from ._op import (_unwrap_index, get_op, op_fn, registered_ops, unwrap,  # noqa
                  wrap)
from .creation import *  # noqa
from .math import *  # noqa
from .math_ext import *  # noqa
from .reduction import *  # noqa
from .manipulation import *  # noqa
from .manipulation_ext import *  # noqa
from .linalg import *  # noqa
from .linalg_ext import *  # noqa
from .logic import *  # noqa
from .random import *  # noqa
from .misc_ext import *  # noqa
from . import fft_ops  # noqa  (namespaced under paddle_tpu.fft)

from ..core.tensor import Tensor
from ..core import enforce as E


def _m(name, f, positional_kw=None):
    """Attach op as a Tensor method. ``positional_kw``: names of paddle's
    positional args that the pure op takes as keywords (e.g. reshape(shape))."""
    import functools
    if positional_kw:
        @functools.wraps(f)
        def meth(self, *args, **kwargs):
            for kw, a in zip(positional_kw, args):
                kwargs[kw] = a
            return f(self, **kwargs)
    else:
        @functools.wraps(f)
        def meth(self, *args, **kwargs):
            return f(self, *args, **kwargs)
    if not hasattr(Tensor, name):
        setattr(Tensor, name, meth)


def _register_tensor_methods():
    # Ops whose pure fn takes only positional tensor args (safe to forward
    # the method call verbatim). Ops with keyword-only config args go in the
    # `kw` table or get explicit adapters below.
    simple = [
        "add", "subtract", "multiply", "divide", "mod", "pow", "abs", "exp",
        "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square", "sin",
        "cos", "tan", "tanh", "sigmoid", "floor", "ceil", "round", "trunc",
        "sign", "reciprocal", "maximum", "minimum", "erf", "erfinv", "matmul",
        "dot", "inner", "outer", "cross", "cholesky", "inv", "det",
        "equal", "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal", "logical_and", "logical_or", "logical_xor",
        "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor",
        "bitwise_not", "isclose", "allclose", "equal_all", "isnan", "isinf",
        "isfinite", "where", "topk", "unique", "t",
        "zero_", "numel", "conj", "real", "imag", "angle", "lerp",
        "clone", "masked_select", "gather_nd",
        "kron", "frac", "digamma", "lgamma", "atan", "asin", "acos",
        "sinh", "cosh", "asinh", "acosh", "atanh", "expm1",
        "heaviside", "hypot", "deg2rad", "rad2deg", "unbind",
    ]
    import sys
    ns = sys.modules[__name__].__dict__
    for name in simple:
        if name in ns:
            _m(name, ns[name])

    kw = {
        "sum": ["axis", "keepdim"],
        "mean": ["axis", "keepdim"],
        "max": ["axis", "keepdim"],
        "min": ["axis", "keepdim"],
        "prod": ["axis", "keepdim"],
        "amax": ["axis", "keepdim"],
        "amin": ["axis", "keepdim"],
        "all": ["axis", "keepdim"],
        "any": ["axis", "keepdim"],
        "argmax": ["axis", "keepdim"],
        "argmin": ["axis", "keepdim"],
        "std": ["axis", "unbiased", "keepdim"],
        "var": ["axis", "unbiased", "keepdim"],
        "median": ["axis", "keepdim"],
        "reshape": ["shape"],
        "transpose": ["perm"],
        "flatten": ["start_axis", "stop_axis"],
        "squeeze": ["axis"],
        "unsqueeze": ["axis"],
        "tile": ["repeat_times"],
        "expand": ["shape"],
        "clip": ["min", "max"],
        "scale": ["scale", "bias"],
        "flip": ["axis"],
        "moveaxis": ["source", "destination"],
        "norm": ["p", "axis", "keepdim"],
        "sort": ["axis", "descending"],
        "argsort": ["axis", "descending"],
        "cumsum": ["axis"],
        "cumprod": ["dim"],
        "logsumexp": ["axis", "keepdim"],
        "logit": ["eps"],
        "nan_to_num": ["nan", "posinf", "neginf"],
        "roll": ["shifts", "axis"],
        "tril": ["diagonal"],
        "triu": ["diagonal"],
        "diagonal": ["offset", "axis1", "axis2"],
        "trace": ["offset", "axis1", "axis2"],
        "repeat_interleave": ["repeats", "axis"],
        "broadcast_to": ["shape"],
        "nonzero": ["as_tuple"],
        "bincount": ["weights", "minlength"],
    }
    for name, kws in kw.items():
        if name in ns:
            _m(name, ns[name], positional_kw=kws)

    # methods needing custom signatures
    def split_m(self, num_or_sections, axis=0):
        return split(self, num_or_sections, axis=axis)
    def chunk_m(self, chunks, axis=0):
        return chunk(self, chunks, axis=axis)
    def cast_m(self, dtype):
        return cast(self, dtype)
    def item_m(self):
        return self._data.item()
    if not hasattr(Tensor, "split"):
        Tensor.split = split_m
        Tensor.chunk = chunk_m
        Tensor.cast = cast_m
    Tensor.mm = lambda self, y: matmul(self, y)
    Tensor.bmm = lambda self, y: matmul(self, y)
    Tensor.unstack = lambda self, axis=0: unbind(self, axis=axis)
    # Mixed positional/keyword adapters (first args are tensors, trailing
    # paddle-positional args map onto kw-only config of the pure fn).
    Tensor.masked_fill = lambda self, mask, value: masked_fill(self, mask, value=value)
    Tensor.gather = lambda self, index, axis=0: gather(self, index, axis=axis)
    Tensor.index_select = lambda self, index, axis=0: index_select(self, index, axis=axis)
    Tensor.take_along_axis = (
        lambda self, indices, axis, broadcast=True: take_along_axis(self, indices, axis=axis))
    Tensor.put_along_axis = (
        lambda self, indices, values, axis, reduce="assign":
        put_along_axis(self, indices, values, axis=axis, reduce=reduce))
    Tensor.scatter = (
        lambda self, index, updates, overwrite=True:
        scatter(self, index, updates, overwrite=overwrite))
    Tensor.tensordot = lambda self, y, axes=2: tensordot(self, y, axes=axes)
    Tensor.index_add = (
        lambda self, index, axis, value: index_add(self, index, axis=axis, value=value))

    # extended surface (linalg_ext / math_ext / manipulation_ext)
    simple2 = [
        "copysign", "nextafter", "i0", "i0e", "i1", "i1e", "sinc",
        "gammaln", "gammainc", "gammaincc", "neg", "sgn", "signbit",
        "isneginf", "isposinf", "isreal", "is_complex", "is_floating_point",
        "is_integer", "floor_mod", "remainder", "take", "mv", "inverse",
        "matrix_transpose", "cdist", "dist", "cov", "corrcoef", "cond",
        "vander", "histogram", "svd", "qr", "eig", "eigvals",
        "lu", "lstsq", "expand_as", "view_as", "atleast_1d", "atleast_2d",
        "atleast_3d", "index_sample", "masked_scatter", "unique_consecutive",
        "mode", "diag_embed", "frexp", "diff", "addmm",
    ]
    for name in simple2:
        if name in ns:
            _m(name, ns[name])

    kw2 = {
        "logcumsumexp": ["axis"],
        "cummin": ["axis"],
        "cummax": ["axis"],
        "nanmedian": ["axis", "keepdim"],
        "nanquantile": ["q", "axis", "keepdim"],
        "bitwise_left_shift": ["y"],
        "bitwise_right_shift": ["y"],
        "renorm": ["p", "axis", "max_norm"],
        "multigammaln": ["p"],
        "kthvalue": ["k", "axis", "keepdim"],
        "unflatten": ["axis", "shape"],
        "tensor_split": ["num_or_indices", "axis"],
        "vector_norm": ["p", "axis", "keepdim"],
    }
    for name, kws in kw2.items():
        if name in ns:
            _m(name, ns[name], positional_kw=kws)

    Tensor.bucketize = (
        lambda self, sorted_sequence, out_int32=False, right=False:
        bucketize(self, sorted_sequence, out_int32=out_int32, right=right))
    Tensor.index_fill = (
        lambda self, index, axis, value: index_fill(self, index, axis, value))
    Tensor.select_scatter = (
        lambda self, values, axis, index:
        select_scatter(self, values, axis, index))
    Tensor.slice_scatter = (
        lambda self, value, axes=None, starts=None, ends=None, strides=None:
        slice_scatter(self, value, axes, starts, ends, strides))
    Tensor.diagonal_scatter = (
        lambda self, y, offset=0, axis1=0, axis2=1:
        diagonal_scatter(self, y, offset, axis1, axis2))
    Tensor.as_strided = (
        lambda self, shape, stride, offset=0:
        as_strided(self, shape, stride, offset))
    Tensor.view = lambda self, shape_or_dtype: view(self, shape_or_dtype)

    # remaining paddle Tensor-method parity: ops whose first arg is the
    # tensor and whose paddle method forwards positionally
    simple3 = [
        "as_complex", "as_real", "atan2", "cholesky_solve", "count_nonzero",
        "diag", "diagflat", "dsplit", "eigvalsh", "floor_divide", "fmax",
        "fmin", "gcd", "histogramdd", "householder_product", "hsplit",
        "increment", "index_put", "is_empty", "lcm", "ldexp", "logaddexp",
        "lu_unpack", "matrix_power", "multinomial", "multiplex", "nanmean",
        "nansum", "ormqr", "pca_lowrank", "pinv", "polar", "polygamma",
        "quantile", "rank", "reduce_as", "reverse", "rot90", "scatter_nd",
        "scatter_nd_add", "shard_index", "slice", "solve", "stanh",
        "strided_slice", "svd_lowrank", "top_p_sampling", "trapezoid",
        "triangular_solve", "vsplit", "istft", "stft",
    ]
    from . import fft_ops as _fft_ops
    ns2 = dict(ns)
    ns2.setdefault("istft", _fft_ops.istft)
    ns2.setdefault("stft", _fft_ops.stft)
    for name in simple3:
        if name in ns2:
            _m(name, ns2[name])
    Tensor.concat = lambda self, *xs, axis=0: concat([self, *xs], axis=axis)
    Tensor.stack = lambda self, *xs, axis=0: stack([self, *xs], axis=axis)
    Tensor.add_n = lambda self, *xs: add_n([self, *xs])
    Tensor.broadcast_tensors = (
        lambda self, *xs: broadcast_tensors([self, *xs]))
    Tensor.cumulative_trapezoid = (
        lambda self, x=None, dx=None, axis=-1:
        cumulative_trapezoid(self, x, dx, axis))
    from .manipulation_ext import tensor_unfold as _tensor_unfold_fn
    Tensor.unfold = (
        lambda self, axis, size, step: _tensor_unfold_fn(self, axis, size, step))
    from .random import exponential_ as _exponential_
    Tensor.exponential_ = lambda self, lam=1.0: _exponential_(self, lam)
    Tensor.multi_dot = lambda self, *xs: multi_dot([self, *xs])


_register_tensor_methods()


# ---------------------------------------------------------------------------
# In-place variants (paddle's `op_`): mutation = rebinding on the Tensor
# facade (core/tensor.py:32). The result ADOPTS the out tensor's grad node
# so autograd still flows — the TPU-native stand-in for the reference's
# inplace version-counter machinery (paddle/fluid/eager/utils.cc
# CheckInplace): XLA arrays are immutable, so "inplace" is an API-surface
# notion only.
# ---------------------------------------------------------------------------
import weakref as _weakref


def _adopt(x: Tensor, out: Tensor) -> Tensor:
    x._data = out._data
    if out._grad_node is not None:
        node, slot = out._grad_node, out._output_slot
        x._grad_node, x._output_slot = node, slot
        if slot < len(node.out_refs):
            node.out_refs[slot] = _weakref.ref(x)
        x.stop_gradient = False
    elif x._grad_node is not None:
        # Tracked tensor modified in-place while grads are off: its old
        # graph no longer describes its value. Poison the node so a later
        # backward errors loudly (the reference's inplace version-counter
        # check, eager/utils.cc CheckInplace) instead of silently using
        # the stale graph.
        from ..autograd.tape import GradNode

        def _poison(*_):
            raise E.PreconditionNotMetError(
                "Tensor was modified by an in-place operation while grad "
                "recording was off; its autograd graph is invalid. "
                "Recompute it or call .detach() before the in-place op.")
        node = GradNode("inplace(no_grad)", _poison, [],
                        [(tuple(x._data.shape), x._data.dtype)])
        x._grad_node, x._output_slot = node, 0
        node.out_refs.append(_weakref.ref(x))
    return x


def _snapshot(x: Tensor) -> Tensor:
    """Freeze x's current (data, graph position) into a fresh Tensor so an
    inplace op can be recorded against the snapshot — x itself is about to
    be re-pointed at the op's output, and recording against x directly
    would make the new node its own input (a graph cycle)."""
    s = Tensor(x._data, stop_gradient=x.stop_gradient)
    if x._grad_node is not None:
        node, slot = x._grad_node, x._output_slot
        s._grad_node, s._output_slot = node, slot
        if slot < len(node.out_refs) and node.out_refs[slot]() is x:
            node.out_refs[slot] = _weakref.ref(s)
        s.stop_gradient = False
    return s


_INPLACE_NAMES = [
    "abs", "acos", "acosh", "add", "addmm", "asin", "asinh", "atan",
    "atanh", "bitwise_and", "bitwise_left_shift", "bitwise_not",
    "bitwise_or", "bitwise_right_shift", "bitwise_xor", "ceil", "clip",
    "copysign", "cos", "cosh", "cumprod", "cumsum", "digamma", "divide",
    "equal", "erfinv", "exp", "expm1", "flatten", "floor", "floor_divide",
    "floor_mod", "frac", "gammainc", "gammaincc", "gammaln", "gcd",
    "greater_equal", "greater_than", "hypot", "i0", "index_fill",
    "index_put", "lcm",
    "ldexp", "lerp", "less_equal", "less_than", "lgamma", "log", "log10",
    "log1p", "log2", "logical_and", "logical_not", "logical_or",
    "logical_xor", "logit", "masked_fill", "masked_scatter", "mod",
    "multigammaln", "multiply", "nan_to_num", "neg", "not_equal",
    "polygamma", "pow", "put_along_axis", "reciprocal", "remainder",
    "renorm", "reshape", "round", "rsqrt", "scale", "scatter", "sigmoid",
    "sin", "sinc", "sinh", "sqrt", "squeeze", "subtract", "tan", "tanh",
    "tril", "triu", "trunc", "unsqueeze", "erf", "square", "index_add",
    # NOT "where": where_(cond, x, y) mutates x (arg 1), not the condition,
    # so the generic first-arg adoption would corrupt the bool cond tensor
]


def _where_(condition, x, y, name=None):
    """paddle.where_ parity: writes the selection into x."""
    return _adopt(x, where(condition, _snapshot(x), y))


def _make_inplace(fn):
    import functools

    @functools.wraps(fn)
    def inplace(x, *args, **kwargs):
        return _adopt(x, fn(_snapshot(x), *args, **kwargs))
    inplace.__name__ = fn.__name__ + "_"
    return inplace


def _register_inplace():
    import sys
    ns = sys.modules[__name__].__dict__
    for name in _INPLACE_NAMES:
        base = ns.get(name)
        if base is None:
            continue
        iname = name + "_"
        method = getattr(Tensor, name, None)
        ns.setdefault(iname, _make_inplace(base))
        if method is not None and not hasattr(Tensor, iname):
            def meth(self, *a, _m=method, **k):
                return _adopt(self, _m(_snapshot(self), *a, **k))
            setattr(Tensor, iname, meth)

    # transpose_/t_/cast_ have method-specific signatures
    def _t_(self):
        return _adopt(self, _snapshot(self).t())
    def _transpose_(self, perm):
        return _adopt(self, transpose(_snapshot(self), perm=perm))
    def _cast_(self, dtype):
        return _adopt(self, cast(_snapshot(self), dtype))
    if not hasattr(Tensor, "t_"):
        Tensor.t_ = _t_
        Tensor.transpose_ = _transpose_
        Tensor.cast_ = _cast_
    ns.setdefault("t_", lambda x: _t_(x))
    ns.setdefault("transpose_", lambda x, perm: _transpose_(x, perm))
    ns.setdefault("cast_", lambda x, dtype: _cast_(x, dtype))

    # random fills (reference: tensor/random.py uniform_/normal_/...)
    from ..framework.random import next_key as _next_key
    import jax as _jax
    import jax.numpy as _jnp

    def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
        x._data = _jax.random.uniform(_next_key(), x._data.shape,
                                      x._data.dtype, min, max)
        return x

    def normal_(x, mean=0.0, std=1.0, seed=0, name=None):
        x._data = mean + std * _jax.random.normal(_next_key(),
                                                  x._data.shape, x._data.dtype)
        return x

    def cauchy_(x, loc=0, scale=1, name=None):
        u = _jax.random.uniform(_next_key(), x._data.shape, x._data.dtype)
        x._data = loc + scale * _jnp.tan(_jnp.pi * (u - 0.5))
        return x

    def geometric_(x, probs, name=None):
        u = _jax.random.uniform(_next_key(), x._data.shape, x._data.dtype)
        x._data = _jnp.ceil(_jnp.log1p(-u) / _jnp.log1p(-probs))
        return x

    for f in (uniform_, normal_, cauchy_, geometric_):
        ns.setdefault(f.__name__, f)
        if not hasattr(Tensor, f.__name__):
            setattr(Tensor, f.__name__, f)


_register_inplace()
where_ = _where_
if not hasattr(Tensor, "where_"):
    Tensor.where_ = lambda self, x, y: _where_(self, x, y)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Reference: tensor/creation.py create_parameter."""
    import jax.numpy as _jnp
    from ..core.dtype import convert_dtype
    from ..core.tensor import Parameter
    import math as _math
    dt = convert_dtype(dtype)
    if default_initializer is not None:
        data = default_initializer(shape, dt)
        if isinstance(data, Tensor):
            data = data._data
    elif is_bias:
        data = _jnp.zeros(shape, dt)
    else:   # Xavier-uniform default, matching nn initializer defaults
        fan_in = shape[0] if shape else 1
        fan_out = shape[-1] if shape else 1
        bound = _math.sqrt(6.0 / (fan_in + fan_out))
        import jax as _jax
        from ..framework.random import next_key as _nk
        data = _jax.random.uniform(_nk(), tuple(shape), dt, -bound, bound)
    return Parameter(data)


def create_tensor(dtype, name=None, persistable=False):
    import jax.numpy as _jnp
    from ..core.dtype import convert_dtype
    return Tensor(_jnp.zeros((), convert_dtype(dtype)))
