"""Aggregate op namespace (the `_C_ops`-equivalent flat op surface,
reference: python/paddle/_C_ops.py re-exporting core.eager.ops). Also attaches
the op set onto Tensor as methods (paddle Tensor method parity)."""
from __future__ import annotations

from ._op import (_unwrap_index, get_op, op_fn, registered_ops, unwrap,  # noqa
                  wrap)
from .creation import *  # noqa
from .math import *  # noqa
from .reduction import *  # noqa
from .manipulation import *  # noqa
from .linalg import *  # noqa
from .logic import *  # noqa
from .random import *  # noqa

from ..core.tensor import Tensor


def _m(name, f, positional_kw=None):
    """Attach op as a Tensor method. ``positional_kw``: names of paddle's
    positional args that the pure op takes as keywords (e.g. reshape(shape))."""
    import functools
    if positional_kw:
        @functools.wraps(f)
        def meth(self, *args, **kwargs):
            for kw, a in zip(positional_kw, args):
                kwargs[kw] = a
            return f(self, **kwargs)
    else:
        @functools.wraps(f)
        def meth(self, *args, **kwargs):
            return f(self, *args, **kwargs)
    if not hasattr(Tensor, name):
        setattr(Tensor, name, meth)


def _register_tensor_methods():
    # Ops whose pure fn takes only positional tensor args (safe to forward
    # the method call verbatim). Ops with keyword-only config args go in the
    # `kw` table or get explicit adapters below.
    simple = [
        "add", "subtract", "multiply", "divide", "mod", "pow", "abs", "exp",
        "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square", "sin",
        "cos", "tan", "tanh", "sigmoid", "floor", "ceil", "round", "trunc",
        "sign", "reciprocal", "maximum", "minimum", "erf", "erfinv", "matmul",
        "dot", "inner", "outer", "cross", "cholesky", "inv", "det",
        "equal", "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal", "logical_and", "logical_or", "logical_xor",
        "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor",
        "bitwise_not", "isclose", "allclose", "equal_all", "isnan", "isinf",
        "isfinite", "where", "topk", "unique", "t",
        "zero_", "numel", "conj", "real", "imag", "angle", "lerp",
        "clone", "masked_select", "gather_nd",
        "kron", "frac", "digamma", "lgamma", "atan", "asin", "acos",
        "sinh", "cosh", "asinh", "acosh", "atanh", "expm1",
        "heaviside", "hypot", "deg2rad", "rad2deg", "unbind",
    ]
    import sys
    ns = sys.modules[__name__].__dict__
    for name in simple:
        if name in ns:
            _m(name, ns[name])

    kw = {
        "sum": ["axis", "keepdim"],
        "mean": ["axis", "keepdim"],
        "max": ["axis", "keepdim"],
        "min": ["axis", "keepdim"],
        "prod": ["axis", "keepdim"],
        "amax": ["axis", "keepdim"],
        "amin": ["axis", "keepdim"],
        "all": ["axis", "keepdim"],
        "any": ["axis", "keepdim"],
        "argmax": ["axis", "keepdim"],
        "argmin": ["axis", "keepdim"],
        "std": ["axis", "unbiased", "keepdim"],
        "var": ["axis", "unbiased", "keepdim"],
        "median": ["axis", "keepdim"],
        "reshape": ["shape"],
        "transpose": ["perm"],
        "flatten": ["start_axis", "stop_axis"],
        "squeeze": ["axis"],
        "unsqueeze": ["axis"],
        "tile": ["repeat_times"],
        "expand": ["shape"],
        "clip": ["min", "max"],
        "scale": ["scale", "bias"],
        "flip": ["axis"],
        "moveaxis": ["source", "destination"],
        "norm": ["p", "axis", "keepdim"],
        "sort": ["axis", "descending"],
        "argsort": ["axis", "descending"],
        "cumsum": ["axis"],
        "cumprod": ["dim"],
        "logsumexp": ["axis", "keepdim"],
        "logit": ["eps"],
        "nan_to_num": ["nan", "posinf", "neginf"],
        "roll": ["shifts", "axis"],
        "tril": ["diagonal"],
        "triu": ["diagonal"],
        "diagonal": ["offset", "axis1", "axis2"],
        "trace": ["offset", "axis1", "axis2"],
        "repeat_interleave": ["repeats", "axis"],
        "broadcast_to": ["shape"],
        "nonzero": ["as_tuple"],
        "bincount": ["weights", "minlength"],
    }
    for name, kws in kw.items():
        if name in ns:
            _m(name, ns[name], positional_kw=kws)

    # methods needing custom signatures
    def split_m(self, num_or_sections, axis=0):
        return split(self, num_or_sections, axis=axis)
    def chunk_m(self, chunks, axis=0):
        return chunk(self, chunks, axis=axis)
    def cast_m(self, dtype):
        return cast(self, dtype)
    def item_m(self):
        return self._data.item()
    if not hasattr(Tensor, "split"):
        Tensor.split = split_m
        Tensor.chunk = chunk_m
        Tensor.cast = cast_m
    Tensor.mm = lambda self, y: matmul(self, y)
    Tensor.bmm = lambda self, y: matmul(self, y)
    Tensor.unstack = lambda self, axis=0: unbind(self, axis=axis)
    # Mixed positional/keyword adapters (first args are tensors, trailing
    # paddle-positional args map onto kw-only config of the pure fn).
    Tensor.masked_fill = lambda self, mask, value: masked_fill(self, mask, value=value)
    Tensor.gather = lambda self, index, axis=0: gather(self, index, axis=axis)
    Tensor.index_select = lambda self, index, axis=0: index_select(self, index, axis=axis)
    Tensor.take_along_axis = (
        lambda self, indices, axis, broadcast=True: take_along_axis(self, indices, axis=axis))
    Tensor.put_along_axis = (
        lambda self, indices, values, axis, reduce="assign":
        put_along_axis(self, indices, values, axis=axis, reduce=reduce))
    Tensor.scatter = (
        lambda self, index, updates, overwrite=True:
        scatter(self, index, updates, overwrite=overwrite))
    Tensor.tensordot = lambda self, y, axes=2: tensordot(self, y, axes=axes)
    Tensor.index_add = (
        lambda self, index, axis, value: index_add(self, index, axis=axis, value=value))


_register_tensor_methods()
