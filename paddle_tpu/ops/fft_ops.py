"""FFT op family (paddle.fft parity).

Reference capability: python/paddle/fft.py (fft_c2c/fft_r2c/fft_c2r phi
kernels backed by cuFFT/pocketfft). TPU-native: jnp.fft lowers to XLA's
FFT HLO, which runs natively on TPU; normalization modes match paddle's
("backward" | "ortho" | "forward"). stft/istft are composed from frame +
fft the way the reference composes them in python (signal.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ._op import op_fn, unwrap, wrap
from ..core import enforce as E

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq", "stft", "istft",
]


def _norm(normalization):
    if normalization not in ("backward", "ortho", "forward"):
        raise E.InvalidArgumentError(
            f"Unexpected norm: {normalization!r} (use backward/ortho/forward)")
    return normalization


def _mk1(jfn, opname):
    @op_fn(name=opname)
    def op(x, *, n=None, axis=-1, norm="backward"):
        return jfn(x, n=n, axis=axis, norm=_norm(norm))

    def api(x, n=None, axis=-1, norm="backward", name=None):
        return op(x, n=n, axis=axis, norm=norm)
    return api


def _mkn(jfn, opname):
    @op_fn(name=opname)
    def op(x, *, s=None, axes=None, norm="backward"):
        return jfn(x, s=s, axes=axes, norm=_norm(norm))

    def api(x, s=None, axes=None, norm="backward", name=None):
        if isinstance(axes, list):
            axes = tuple(axes)
        if isinstance(s, list):
            s = tuple(s)
        return op(x, s=s, axes=axes, norm=norm)
    return api


def _mk2(jfn, opname):
    nd = _mkn(jfn, opname)

    def api(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return nd(x, s=s, axes=axes, norm=norm)
    return api


fft = _mk1(jnp.fft.fft, "fft")
ifft = _mk1(jnp.fft.ifft, "ifft")
rfft = _mk1(jnp.fft.rfft, "rfft")
irfft = _mk1(jnp.fft.irfft, "irfft")
hfft = _mk1(jnp.fft.hfft, "hfft")
ihfft = _mk1(jnp.fft.ihfft, "ihfft")

fftn = _mkn(jnp.fft.fftn, "fftn")
ifftn = _mkn(jnp.fft.ifftn, "ifftn")
rfftn = _mkn(jnp.fft.rfftn, "rfftn")
irfftn = _mkn(jnp.fft.irfftn, "irfftn")

fft2 = _mk2(jnp.fft.fftn, "fft2")
ifft2 = _mk2(jnp.fft.ifftn, "ifft2")
rfft2 = _mk2(jnp.fft.rfftn, "rfft2")
irfft2 = _mk2(jnp.fft.irfftn, "irfft2")


def _hfftn(x, s=None, axes=None, norm="backward"):
    # hermitian-input nd fft: conj-reverse trick over the last axis
    return jnp.fft.irfftn(jnp.conj(x), s=s, axes=axes,
                          norm={"backward": "forward", "forward": "backward",
                                "ortho": "ortho"}[norm])


def _ihfftn(x, s=None, axes=None, norm="backward"):
    return jnp.conj(jnp.fft.rfftn(x, s=s, axes=axes,
                                  norm={"backward": "forward",
                                        "forward": "backward",
                                        "ortho": "ortho"}[norm]))


hfftn = _mkn(_hfftn, "hfftn")
ihfftn = _mkn(_ihfftn, "ihfftn")
hfft2 = _mk2(_hfftn, "hfft2")
ihfft2 = _mk2(_ihfftn, "ihfft2")


@op_fn(name="fftshift")
def _fftshift(x, *, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def fftshift(x, axes=None, name=None):
    if isinstance(axes, list):
        axes = tuple(axes)
    return _fftshift(x, axes=axes)


@op_fn(name="ifftshift")
def _ifftshift(x, *, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def ifftshift(x, axes=None, name=None):
    if isinstance(axes, list):
        axes = tuple(axes)
    return _ifftshift(x, axes=axes)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.dtype import convert_dtype
    arr = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        arr = arr.astype(convert_dtype(dtype))
    return wrap(arr)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.dtype import convert_dtype
    arr = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        arr = arr.astype(convert_dtype(dtype))
    return wrap(arr)


@op_fn(name="stft_op")
def _stft(x, window, *, n_fft, hop_length, center, pad_mode, normalized,
          onesided):
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    n = x.shape[-1]
    n_frames = 1 + (n - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    frames = x[..., idx] * window                       # [..., frames, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1) if onesided \
        else jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return jnp.swapaxes(spec, -1, -2)                   # [..., freq, frames]


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Reference: python/paddle/signal.py stft."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = unwrap(window)
    if win_length < n_fft:                              # center-pad window
        lp = (n_fft - win_length) // 2
        win = jnp.pad(win, (lp, n_fft - win_length - lp))
    return _stft(x, win, n_fft=n_fft, hop_length=hop_length, center=center,
                 pad_mode=pad_mode, normalized=normalized, onesided=onesided)


@op_fn(name="istft_op")
def _istft(spec, window, *, n_fft, hop_length, center, normalized,
           onesided, length, return_complex):
    spec = jnp.swapaxes(spec, -1, -2)                   # [..., frames, freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
              else jnp.fft.ifft(spec, axis=-1))
    if not return_complex:
        frames = frames.real if jnp.iscomplexobj(frames) else frames
    frames = frames * window
    n_frames = frames.shape[-2]
    out_len = n_fft + hop_length * (n_frames - 1)
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :]).reshape(-1)
    batch = frames.shape[:-2]
    flat = frames.reshape(batch + (-1,))
    out = jnp.zeros(batch + (out_len,), flat.dtype)
    out = out.at[..., idx].add(flat)
    wsq = jnp.zeros((out_len,), window.dtype)
    wsq = wsq.at[idx].add(jnp.broadcast_to(window * window,
                                           (n_frames, n_fft)).reshape(-1))
    out = out / jnp.where(wsq > 1e-11, wsq, 1.0)
    if center:
        pad = n_fft // 2
        out = out[..., pad:out_len - pad]
    if length is not None:
        out = out[..., :length]
    return out


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Reference: python/paddle/signal.py istft (overlap-add)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = unwrap(window)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        win = jnp.pad(win, (lp, n_fft - win_length - lp))
    return _istft(x, win, n_fft=n_fft, hop_length=hop_length, center=center,
                  normalized=normalized, onesided=onesided, length=length,
                  return_complex=return_complex)
