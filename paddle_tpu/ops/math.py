"""Elementwise & scalar math ops (paddle.tensor.math parity,
python/paddle/tensor/math.py). Each op is a pure jnp/lax function — XLA fuses
chains of these into single TPU kernels, replacing the reference's
hand-written elementwise CUDA machinery (paddle/phi/kernels/funcs/elementwise_base.h)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ._op import op_fn, unwrap, wrap
from ..core.tensor import Tensor


@op_fn
def add(x, y):
    return jnp.add(x, y)


@op_fn
def subtract(x, y):
    return jnp.subtract(x, y)


@op_fn
def multiply(x, y):
    return jnp.multiply(x, y)


@op_fn
def divide(x, y):
    return jnp.divide(x, y)


@op_fn(differentiable=False)
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@op_fn
def mod(x, y):
    return jnp.mod(x, y)


remainder = mod


@op_fn
def pow(x, y):
    return jnp.power(x, y)


@op_fn
def scale(x, *, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@op_fn
def abs(x):
    return jnp.abs(x)


@op_fn
def exp(x):
    return jnp.exp(x)


@op_fn
def expm1(x):
    return jnp.expm1(x)


@op_fn
def log(x):
    return jnp.log(x)


@op_fn
def log2(x):
    return jnp.log2(x)


@op_fn
def log10(x):
    return jnp.log10(x)


@op_fn
def log1p(x):
    return jnp.log1p(x)


@op_fn
def sqrt(x):
    return jnp.sqrt(x)


@op_fn
def rsqrt(x):
    return jax.lax.rsqrt(x)


@op_fn
def square(x):
    return jnp.square(x)


@op_fn
def sin(x):
    return jnp.sin(x)


@op_fn
def cos(x):
    return jnp.cos(x)


@op_fn
def tan(x):
    return jnp.tan(x)


@op_fn
def asin(x):
    return jnp.arcsin(x)


@op_fn
def acos(x):
    return jnp.arccos(x)


@op_fn
def atan(x):
    return jnp.arctan(x)


@op_fn
def atan2(x, y):
    return jnp.arctan2(x, y)


@op_fn
def sinh(x):
    return jnp.sinh(x)


@op_fn
def cosh(x):
    return jnp.cosh(x)


@op_fn
def tanh(x):
    return jnp.tanh(x)


@op_fn
def asinh(x):
    return jnp.arcsinh(x)


@op_fn
def acosh(x):
    return jnp.arccosh(x)


@op_fn
def atanh(x):
    return jnp.arctanh(x)


@op_fn(differentiable=False)
def floor(x):
    return jnp.floor(x)


@op_fn(differentiable=False)
def ceil(x):
    return jnp.ceil(x)


@op_fn(differentiable=False)
def round(x):
    return jnp.round(x)


@op_fn(differentiable=False)
def trunc(x):
    return jnp.trunc(x)


@op_fn
def frac(x):
    return x - jnp.trunc(x)


@op_fn(differentiable=False)
def sign(x):
    return jnp.sign(x)


@op_fn
def reciprocal(x):
    return 1.0 / x


@op_fn
def clip(x, *, min=None, max=None):
    return jnp.clip(x, min, max)


@op_fn
def maximum(x, y):
    return jnp.maximum(x, y)


@op_fn
def minimum(x, y):
    return jnp.minimum(x, y)


@op_fn
def fmax(x, y):
    return jnp.fmax(x, y)


@op_fn
def fmin(x, y):
    return jnp.fmin(x, y)


@op_fn
def erf(x):
    return jax.lax.erf(x)


@op_fn
def erfinv(x):
    return jax.lax.erf_inv(x)


@op_fn
def sigmoid(x):
    return jax.nn.sigmoid(x)


@op_fn
def logit(x, *, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@op_fn
def lerp(x, y, weight):
    return x + weight * (y - x)


@op_fn
def stanh(x, *, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@op_fn
def softplus(x, *, beta=1.0, threshold=20.0):
    # Clamp the untaken branch: where's VJP multiplies its cotangent by 0,
    # and 0 * inf (from exp overflow) would poison the grad with NaN.
    safe = jnp.minimum(x * beta, threshold)
    return jnp.where(x * beta > threshold, x, jnp.log1p(jnp.exp(safe)) / beta)


@op_fn
def softsign(x):
    return x / (1.0 + jnp.abs(x))


@op_fn
def cumsum(x, *, axis=None):
    return jnp.cumsum(x, axis=axis)


@op_fn
def cumprod(x, *, dim=None):
    return jnp.cumprod(x, axis=dim)


@op_fn
def cummax_values(x, *, axis=None):
    return jax.lax.cummax(x, axis=axis if axis is not None else 0)


@op_fn
def logsumexp(x, *, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


@op_fn
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


def add_n(inputs):
    """paddle.add_n parity: sum of a list of tensors."""
    from functools import reduce
    if isinstance(inputs, Tensor):
        return inputs
    return reduce(lambda a, b: add(a, b), inputs)


@op_fn(differentiable=False)
def isnan(x):
    return jnp.isnan(x)


@op_fn(differentiable=False)
def isinf(x):
    return jnp.isinf(x)


@op_fn(differentiable=False)
def isfinite(x):
    return jnp.isfinite(x)


@op_fn
def angle(x):
    return jnp.angle(x)


@op_fn
def conj(x):
    return jnp.conj(x)


@op_fn
def real(x):
    return jnp.real(x)


@op_fn
def imag(x):
    return jnp.imag(x)


@op_fn
def deg2rad(x):
    return jnp.deg2rad(x)


@op_fn
def rad2deg(x):
    return jnp.rad2deg(x)


@op_fn
def digamma(x):
    return jax.scipy.special.digamma(x)


@op_fn
def lgamma(x):
    return jax.scipy.special.gammaln(x)


@op_fn
def polygamma(x, *, n=0):
    return jax.scipy.special.polygamma(n, x)


@op_fn
def gcd(x, y):
    return jnp.gcd(x, y)


@op_fn(differentiable=False)
def lcm(x, y):
    return jnp.lcm(x, y)


@op_fn
def heaviside(x, y):
    return jnp.heaviside(x, y)


@op_fn
def hypot(x, y):
    return jnp.hypot(x, y)


@op_fn
def ldexp(x, y):
    return x * jnp.power(2.0, y)


@op_fn
def nan_to_num(x, *, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@op_fn
def trapezoid(y, *, dx=1.0, axis=-1):
    return jax.scipy.integrate.trapezoid(y, dx=dx, axis=axis)


def increment(x, value=1.0):
    """In-place counter increment (paddle.increment parity). Grad-breaking by
    design: mutates the handle outside the tape — intended for step counters
    and other stop_gradient bookkeeping tensors, like the reference op."""
    x._data = x._data + value
    return x
