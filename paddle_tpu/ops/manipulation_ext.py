"""Manipulation op long tail (paddle.tensor.manipulation parity).

Reference capability: python/paddle/tensor/manipulation.py (split/scatter
families, strided views). TPU-native: all views are functional gathers /
slices — XLA turns contiguous slices into zero-copy bitcasts where
possible, so there is no stride machinery to port.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._op import op_fn, unwrap, wrap
from ..core import enforce as E

# this module defines a public `slice` op (paddle API name) — keep a
# handle on the builtin for internal indexing
_py_slice = slice

__all__ = [
    "atleast_1d", "atleast_2d", "atleast_3d", "as_strided", "view",
    "view_as", "unflatten", "expand_as", "tensor_split", "hsplit",
    "vsplit", "dsplit", "select_scatter", "slice_scatter",
    "diagonal_scatter", "index_fill", "index_sample", "masked_scatter",
    "reverse", "slice", "strided_slice", "unique_consecutive", "unstack",
    "shard_index", "kthvalue", "mode", "diag_embed", "broadcast_tensors",
    "crop", "top_p_sampling", "is_empty", "tensor_unfold",
]


def is_empty(x, name=None):
    return wrap(jnp.asarray(unwrap(x).size == 0))


@op_fn(name="tensor_unfold_op")
def _tensor_unfold(x, *, axis, size, step):
    axis = axis % x.ndim
    n = x.shape[axis]
    n_windows = (n - size) // step + 1
    idx = jnp.arange(n_windows)[:, None] * step + jnp.arange(size)[None, :]
    moved = jnp.moveaxis(x, axis, -1)
    win = moved[..., idx]                     # [..., n_windows, size]
    # paddle places the window axis where `axis` was, size last
    return jnp.moveaxis(win, -2, axis)


def tensor_unfold(x, axis, size, step, name=None):
    """paddle.unfold on a Tensor (sliding windows along one axis;
    reference: tensor/manipulation.py unfold). The nn.functional.unfold
    (im2col) keeps the plain `unfold` name, as in the reference."""
    return _tensor_unfold(x, axis=int(axis), size=int(size), step=int(step))


def _atleast(nd):
    def impl(*inputs, name=None):
        outs = []
        for x in inputs:
            a = unwrap(x)
            a = jnp.asarray(a)
            while a.ndim < nd:
                # paddle appends trailing dims for atleast_3d, leading for 1d/2d
                if nd == 3 and a.ndim == 2:
                    a = a[:, :, None]
                else:
                    a = a[None, ...]
            outs.append(wrap(a))
        return outs[0] if len(outs) == 1 else outs
    return impl


atleast_1d = _atleast(1)
atleast_2d = _atleast(2)
atleast_3d = _atleast(3)


@op_fn(name="as_strided_op")
def _as_strided(x, *, shape, stride, offset=0):
    # functional gather equivalent of the strided view
    flat = x.reshape(-1)
    idx = jnp.asarray(offset)
    for dim, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(dim) * st
    return flat[idx.reshape(shape)]


def as_strided(x, shape, stride, offset=0, name=None):
    return _as_strided(x, shape=tuple(shape), stride=tuple(stride),
                       offset=offset)


def view(x, shape_or_dtype, name=None):
    from ..core.dtype import convert_dtype
    a = unwrap(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        from .manipulation import reshape
        return reshape(x, shape=shape_or_dtype)
    return wrap(a.view(convert_dtype(shape_or_dtype)))


def view_as(x, other, name=None):
    from .manipulation import reshape
    return reshape(x, shape=list(unwrap(other).shape))


@op_fn(name="unflatten_op")
def _unflatten(x, *, axis, shape):
    axis = axis % x.ndim
    new = x.shape[:axis] + tuple(shape) + x.shape[axis + 1:]
    return x.reshape(new)


def unflatten(x, axis, shape, name=None):
    shape = [int(s) for s in (unwrap(shape).tolist()
                              if hasattr(unwrap(shape), "tolist") else shape)]
    return _unflatten(x, axis=int(axis), shape=tuple(shape))


def expand_as(x, y, name=None):
    from .manipulation import broadcast_to
    return broadcast_to(x, shape=list(unwrap(y).shape))


def _split_indices(n, indices_or_sections, axis_len):
    if isinstance(indices_or_sections, int):
        return indices_or_sections
    return [int(i) for i in indices_or_sections]


def tensor_split(x, num_or_indices, axis=0, name=None):
    a = unwrap(x)
    pieces = jnp.array_split(a, _split_indices(a.shape[axis], num_or_indices,
                                               a.shape[axis]), axis=axis)
    return [wrap(p) for p in pieces]


def hsplit(x, num_or_indices, name=None):
    a = unwrap(x)
    axis = 0 if a.ndim == 1 else 1
    return tensor_split(x, num_or_indices, axis=axis)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


@op_fn(name="select_scatter_op")
def _select_scatter(x, values, *, axis, index):
    return jax.lax.dynamic_update_index_in_dim(
        x, values.astype(x.dtype), index, axis)


def select_scatter(x, values, axis, index, name=None):
    return _select_scatter(x, values, axis=int(axis), index=int(index))


@op_fn(name="slice_scatter_op")
def _slice_scatter(x, value, *, axes, starts, ends, strides):
    idx = [_py_slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = _py_slice(st, en, sd)
    return x.at[tuple(idx)].set(value.astype(x.dtype))


def slice_scatter(x, value, axes=None, starts=None, ends=None, strides=None,
                  name=None):
    a = unwrap(x)
    axes = list(range(a.ndim)) if axes is None else [int(v) for v in axes]
    starts = [0] * len(axes) if starts is None else [int(v) for v in starts]
    ends = ([a.shape[ax] for ax in axes] if ends is None
            else [int(v) for v in ends])
    strides = [1] * len(axes) if strides is None else [int(v) for v in strides]
    return _slice_scatter(x, value, axes=tuple(axes), starts=tuple(starts),
                          ends=tuple(ends), strides=tuple(strides))


@op_fn(name="diagonal_scatter_op")
def _diagonal_scatter(x, y, *, offset=0, axis1=0, axis2=1):
    moved = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    m, n = moved.shape[-2], moved.shape[-1]
    rows = jnp.arange(m)[:, None]
    cols = jnp.arange(n)[None, :]
    mask = (cols - rows) == offset
    k = min(m, n - offset) if offset >= 0 else min(m + offset, n)
    diag = jnp.zeros(moved.shape, moved.dtype)
    r0 = max(0, -offset)
    c0 = max(0, offset)
    upd = jnp.zeros(moved.shape[:-2] + (m, n), moved.dtype)
    ii = jnp.arange(k)
    upd = upd.at[..., r0 + ii, c0 + ii].set(y.astype(x.dtype))
    out = jnp.where(mask, upd, moved)
    return jnp.moveaxis(out, (-2, -1), (axis1, axis2))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    return _diagonal_scatter(x, y, offset=int(offset), axis1=int(axis1),
                             axis2=int(axis2))


@op_fn(name="index_fill_op")
def _index_fill(x, index, *, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[index].set(value)
    return jnp.moveaxis(moved, 0, axis)


def index_fill(x, index, axis, value, name=None):
    from ..core.tensor import Tensor
    if isinstance(value, Tensor):
        value = unwrap(value)
    return _index_fill(x, index, axis=int(axis), value=value)


@op_fn(name="index_sample_op")
def _index_sample(x, index):
    rows = jnp.arange(x.shape[0])[:, None]
    return x[rows, index]


def index_sample(x, index, name=None):
    return _index_sample(x, index)


@op_fn(name="masked_scatter_op")
def _masked_scatter(x, mask, value):
    mask_b = jnp.broadcast_to(mask, x.shape)
    flat_m = mask_b.reshape(-1)
    # position among True entries for each element
    order = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
    src = value.reshape(-1)
    take = jnp.clip(order, 0, src.shape[0] - 1)
    return jnp.where(flat_m, src[take], x.reshape(-1)).reshape(x.shape)


def masked_scatter(x, mask, value, name=None):
    return _masked_scatter(x, mask, value)


def reverse(x, axis, name=None):
    from .manipulation import flip
    return flip(x, axis=axis)


@op_fn(name="slice_op")
def _slice(input, *, axes, starts, ends):
    idx = [_py_slice(None)] * input.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = _py_slice(st, en)
    return input[tuple(idx)]


def slice(input, axes, starts, ends):
    starts = [int(unwrap(s)) if hasattr(s, "item") or hasattr(s, "_data")
              else int(s) for s in starts]
    ends = [int(unwrap(e)) if hasattr(e, "item") or hasattr(e, "_data")
            else int(e) for e in ends]
    return _slice(input, axes=tuple(int(a) for a in axes),
                  starts=tuple(starts), ends=tuple(ends))


@op_fn(name="strided_slice_op")
def _strided_slice(x, *, axes, starts, ends, strides):
    idx = [_py_slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = _py_slice(st, en, sd)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    return _strided_slice(x, axes=tuple(int(a) for a in axes),
                          starts=tuple(int(s) for s in starts),
                          ends=tuple(int(e) for e in ends),
                          strides=tuple(int(s) for s in strides))


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    """Deduplicate consecutive runs (reference: manipulation.py
    unique_consecutive). Result size is data-dependent — eager-only, like
    the reference's dynamic-shape ops."""
    import numpy as np
    a = np.asarray(unwrap(x))
    if axis is None:
        a = a.reshape(-1)
        keep = np.ones(a.shape[0], bool)
        keep[1:] = a[1:] != a[:-1]
        out = a[keep]
        results = [wrap(jnp.asarray(out))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            results.append(wrap(jnp.asarray(inv.astype(dtype))))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, a.shape[0]))
            results.append(wrap(jnp.asarray(counts.astype(dtype))))
        return results[0] if len(results) == 1 else tuple(results)
    moved = np.moveaxis(a, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    keep = np.ones(flat.shape[0], bool)
    keep[1:] = (flat[1:] != flat[:-1]).any(axis=1)
    out = np.moveaxis(moved[keep], 0, axis)
    results = [wrap(jnp.asarray(out))]
    if return_inverse:
        results.append(wrap(jnp.asarray((np.cumsum(keep) - 1).astype(dtype))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, flat.shape[0]))
        results.append(wrap(jnp.asarray(counts.astype(dtype))))
    return results[0] if len(results) == 1 else tuple(results)


def unstack(x, axis=0, num=None, name=None):
    from .manipulation import unbind
    return unbind(x, axis=axis)


@op_fn(differentiable=False, name="shard_index_op")
def _shard_index(input, *, index_num, nshards, shard_id, ignore_value):
    size = (index_num + nshards - 1) // nshards
    lo, hi = shard_id * size, (shard_id + 1) * size
    inside = (input >= lo) & (input < hi)
    return jnp.where(inside, input - lo, ignore_value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    if not 0 <= shard_id < nshards:
        raise E.InvalidArgumentError(
            f"shard_id ({shard_id}) must be in [0, {nshards})")
    return _shard_index(input, index_num=index_num, nshards=nshards,
                        shard_id=shard_id, ignore_value=ignore_value)


@op_fn(name="kthvalue_op")
def _kthvalue(x, *, k, axis=-1, keepdim=False):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    v = jnp.take(vals, k - 1, axis=axis)
    i = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        v = jnp.expand_dims(v, axis)
        i = jnp.expand_dims(i, axis)
    return v, i.astype(jnp.int64)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return _kthvalue(x, k=int(k), axis=int(axis), keepdim=keepdim)


@op_fn(name="mode_op")
def _mode(x, *, axis=-1, keepdim=False):
    moved = jnp.moveaxis(x, axis, -1)
    srt = jnp.sort(moved, axis=-1)
    arg = jnp.argsort(moved, axis=-1)
    n = srt.shape[-1]
    # run-length: count how many of the following entries equal this one
    eq = srt[..., :, None] == srt[..., None, :]
    counts = jnp.sum(eq, axis=-1)
    best = jnp.argmax(counts, axis=-1)
    v = jnp.take_along_axis(srt, best[..., None], axis=-1)[..., 0]
    i = jnp.take_along_axis(arg, best[..., None], axis=-1)[..., 0]
    if keepdim:
        v = jnp.expand_dims(v, axis)
        i = jnp.expand_dims(i, axis)
    return v, i.astype(jnp.int64)


def mode(x, axis=-1, keepdim=False, name=None):
    return _mode(x, axis=int(axis), keepdim=keepdim)


@op_fn(name="diag_embed_op")
def _diag_embed(input, *, offset=0, dim1=-2, dim2=-1):
    last = input.shape[-1]
    size = last + abs(offset)
    out = jnp.zeros(input.shape[:-1] + (size, size), input.dtype)
    ii = jnp.arange(last)
    r0 = max(0, -offset)
    c0 = max(0, offset)
    out = out.at[..., r0 + ii, c0 + ii].set(input)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
    # place the two new axes at dim1/dim2
    order = {}
    order[d1] = nd - 2
    order[d2] = nd - 1
    rest = iter(perm)
    full = [order[i] if i in order else next(rest) for i in range(nd)]
    return jnp.transpose(out, full)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    return _diag_embed(input, offset=int(offset), dim1=int(dim1),
                       dim2=int(dim2))


def broadcast_tensors(inputs, name=None):
    arrs = [unwrap(i) for i in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [wrap(jnp.broadcast_to(a, shape)) for a in arrs]


@op_fn(name="crop_op")
def _crop(x, *, shape, offsets):
    idx = tuple(_py_slice(o, o + s)
                for o, s in zip(offsets, shape))
    return x[idx]


def crop(x, shape=None, offsets=None, name=None):
    a = unwrap(x)
    shape = list(a.shape) if shape is None else [
        a.shape[i] if int(s) == -1 else int(s) for i, s in enumerate(shape)]
    offsets = [0] * a.ndim if offsets is None else [int(o) for o in offsets]
    return _crop(x, shape=tuple(shape), offsets=tuple(offsets))


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling (reference: tensor/manipulation.py
    top_p_sampling — phi top_p_sampling kernel). Returns (values, ids)."""
    import numpy as np
    a = unwrap(x)
    p = unwrap(ps)
    sorted_idx = jnp.argsort(-a, axis=-1)
    sorted_logits = jnp.take_along_axis(a, sorted_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs <= p[..., None]          # keep first token always
    masked = jnp.where(keep, probs, 0.0)
    masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
    key = jax.random.key(np.random.randint(0, 2**31) if seed in (None, -1)
                         else int(seed))
    choice = jax.random.categorical(key, jnp.log(masked + 1e-30), axis=-1)
    ids = jnp.take_along_axis(sorted_idx, choice[..., None], axis=-1)
    vals = jnp.take_along_axis(a, ids, axis=-1)
    return wrap(vals), wrap(ids.astype(jnp.int64))
