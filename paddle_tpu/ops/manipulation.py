"""Shape/layout manipulation ops (paddle.tensor.manipulation parity).

On TPU these are metadata or cheap relayout ops for XLA — the equivalent of
the reference's zero-copy stride kernels (paddle/phi/kernels/stride/) without
the aliasing hazards: arrays are immutable, so "views" are safe by
construction and XLA elides copies where layouts permit."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from ._op import op_fn, unwrap, wrap, _unwrap_index
from ..core import enforce as E


@op_fn
def reshape(x, *, shape):
    return jnp.reshape(x, shape)


@op_fn
def transpose(x, *, perm):
    return jnp.transpose(x, axes=perm)


def t(x):
    if x.ndim <= 1:
        return x
    return transpose(x, perm=list(range(x.ndim))[::-1])


@op_fn
def moveaxis(x, *, source, destination):
    return jnp.moveaxis(x, source, destination)


@op_fn
def swapaxes(x, *, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


@op_fn
def flatten(x, *, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    sa = start_axis % nd
    so = stop_axis % nd
    shape = x.shape[:sa] + (-1,) + x.shape[so + 1:]
    return jnp.reshape(x, shape)


@op_fn
def squeeze(x, *, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


@op_fn
def unsqueeze(x, *, axis):
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, axis)


def concat(xs, axis=0):
    return _concat(*xs, axis=axis)


@op_fn(name="concat")
def _concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def stack(xs, axis=0):
    return _stack(*xs, axis=axis)


@op_fn(name="stack")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


@op_fn
def split_op(x, *, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    # sections list (may contain -1)
    secs = list(num_or_sections)
    total = x.shape[axis]
    if -1 in secs:
        known = sum(s for s in secs if s != -1)
        secs[secs.index(-1)] = total - known
    points = np.cumsum(secs)[:-1].tolist()
    return tuple(jnp.split(x, points, axis=axis))


def split(x, num_or_sections, axis=0):
    return list(split_op(x, num_or_sections=num_or_sections, axis=axis))


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0):
    n = x.shape[axis]
    parts = split(x, n, axis=axis)
    return [squeeze(p, axis=axis) for p in parts]


@op_fn
def tile(x, *, repeat_times):
    return jnp.tile(x, repeat_times)


@op_fn
def expand(x, *, shape):
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s == -1 else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@op_fn
def broadcast_to(x, *, shape):
    return jnp.broadcast_to(x, shape)


def broadcast_tensors(inputs):
    arrs = jnp.broadcast_arrays(*[unwrap(i) for i in inputs])
    return [wrap(a) for a in arrs]


def broadcast_shape(s1, s2):
    return list(np.broadcast_shapes(tuple(s1), tuple(s2)))


@op_fn
def flip(x, *, axis):
    return jnp.flip(x, axis=axis)


@op_fn
def roll(x, *, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@op_fn
def rot90(x, *, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


@op_fn
def pad(x, *, pad, mode="constant", value=0.0, data_format="NCHW"):
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle semantics (python/paddle/nn/functional/common.py pad): the
        # FIRST pair applies to the LAST dim (pad_left/right on W, then
        # pad_top/bottom on H, ...), so the pair list reverses onto the dims.
        k = len(pad) // 2
        width = [(0, 0)] * (nd - k)
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(k)]
        width += pairs[::-1]
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, width, mode=jmode, constant_values=value)
    return jnp.pad(x, width, mode=jmode)


@op_fn
def cast_f(x, *, dtype):
    return x.astype(dtype)


def cast(x, dtype):
    dt = dtypes.convert_dtype(dtype)
    if dtypes.is_floating_point(dt) or dtypes.is_complex(dt):
        return cast_f(x, dtype=dt)
    # Integer/bool target: non-differentiable path.
    return wrap(unwrap(x).astype(dt))


@op_fn(name="getitem")
def _getitem_pure(x, *, idx):
    return x[idx]


def getitem(x, idx):
    return _getitem_pure(x, idx=_unwrap_index(idx))


@op_fn
def gather(x, index, *, axis=0):
    return jnp.take(x, index.astype(jnp.int32) if hasattr(index, "astype") else index, axis=axis)


@op_fn
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@op_fn
def index_select(x, index, *, axis=0):
    return jnp.take(x, index, axis=axis)


@op_fn
def take_along_axis(x, indices, *, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


@op_fn
def put_along_axis(x, indices, values, *, axis, reduce="assign"):
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    dims = list(range(x.ndim))
    # scatter-add/mul via .at
    idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in dims])
           for d, s in enumerate(x.shape)]
    idx[axis] = indices
    if reduce == "add":
        return x.at[tuple(idx)].add(values)
    if reduce in ("mul", "multiply"):
        return x.at[tuple(idx)].multiply(values)
    raise E.InvalidArgumentError(f"unsupported reduce: {reduce}")


@op_fn
def scatter(x, index, updates, *, overwrite=True):
    """paddle.scatter parity: scatter rows of `updates` into x at `index`."""
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@op_fn
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    zeros = jnp.zeros(shape, dtype=unwrap(updates).dtype)
    return scatter_nd_add(wrap(zeros), index, updates)


@op_fn
def index_add(x, index, *, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[index].add(jnp.moveaxis(value, axis, 0))
    return jnp.moveaxis(moved, 0, axis)


@op_fn
def index_put(x, indices, value, *, accumulate=False):
    idx = tuple(indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


@op_fn
def where(condition, x, y):
    return jnp.where(condition, x, y)


@op_fn(differentiable=False)
def nonzero(x, *, as_tuple=False):
    idx = jnp.nonzero(x)
    if as_tuple:
        return idx
    return jnp.stack(idx, axis=1)


@op_fn(differentiable=False)
def masked_select_nondiff(x, mask):
    return x[mask]


def masked_select(x, mask):
    return masked_select_nondiff(x, mask)


@op_fn
def masked_fill(x, mask, *, value):
    return jnp.where(mask, value, x)


@op_fn
def sort(x, *, axis=-1, descending=False):
    s = jnp.sort(x, axis=axis)
    if descending:
        s = jnp.flip(s, axis=axis)
    return s


@op_fn(differentiable=False)
def argsort(x, *, axis=-1, descending=False, stable=True):
    s = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return s


def topk(x, k, axis=-1, largest=True, sorted=True):
    """paddle.topk parity: returns (values, indices). Values are
    differentiable (gather of x); indices come from lax.top_k."""
    xr = unwrap(x)
    if not largest:
        xr_n = -xr
    else:
        xr_n = xr
    if axis != -1 and axis != xr.ndim - 1:
        xr_m = jnp.moveaxis(xr_n, axis, -1)
    else:
        xr_m = xr_n
    _, idx = jax.lax.top_k(xr_m, k)
    if axis != -1 and axis != xr.ndim - 1:
        idx = jnp.moveaxis(idx, -1, axis)
    indices = wrap(idx.astype(jnp.int64))
    values = take_along_axis(x, wrap(idx), axis=axis)
    return values, indices


@op_fn(differentiable=False)
def unique_op(x):
    return jnp.unique(x)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    r = jnp.unique(unwrap(x), return_index=return_index,
                   return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if isinstance(r, tuple):
        return tuple(wrap(v) for v in r)
    return wrap(r)


@op_fn(differentiable=False)
def searchsorted(sorted_sequence, values, *, right=False):
    return jnp.searchsorted(sorted_sequence, values, side="right" if right else "left")


@op_fn(differentiable=False)
def bincount(x, *, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


@op_fn
def repeat_interleave(x, *, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@op_fn
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@op_fn
def as_complex(x):
    return x[..., 0] + 1j * x[..., 1]


@op_fn
def diagonal(x, *, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@op_fn
def trace(x, *, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@op_fn
def kron(x, y):
    return jnp.kron(x, y)


def numel(x):
    return wrap(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1))


def shape(x):
    return wrap(jnp.asarray(unwrap(x).shape, dtype=jnp.int32))


@op_fn(differentiable=False)
def one_hot_nd(x, *, num_classes):
    return jax.nn.one_hot(x, num_classes)


def one_hot(x, num_classes):
    return one_hot_nd(x, num_classes=num_classes)


@op_fn
def tensordot(x, y, *, axes=2):
    return jnp.tensordot(x, y, axes=axes)
