"""Linear algebra ops (paddle.tensor.linalg + paddle.linalg parity).

Matmuls are the MXU workload: everything here lowers to XLA dot_general with
a configurable precision (bf16-first on TPU). Replaces the reference's cuBLAS
bindings (paddle/phi/kernels/funcs/blas/)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.flags import flag_value
from ._op import op_fn, unwrap, wrap


def _precision():
    p = flag_value("default_matmul_precision")
    return None if p == "default" else p


@op_fn
def matmul(x, y, *, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y, precision=_precision())


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return matmul(x, y)


@op_fn
def dot(x, y):
    # paddle.dot: 1-D or batched 1-D inner product.
    return jnp.sum(x * y, axis=-1)


@op_fn
def inner(x, y):
    return jnp.inner(x, y)


@op_fn
def outer(x, y):
    return jnp.outer(x, y)


@op_fn
def cross(x, y, *, axis=-1):
    return jnp.cross(x, y, axis=axis)


@op_fn(name="einsum")
def _einsum(*operands, equation):
    return jnp.einsum(equation, *operands, precision=_precision())


def einsum(equation, *operands):
    return _einsum(*operands, equation=equation)


@op_fn
def norm(x, *, p=None, axis=None, keepdim=False):
    if p is None:
        p = 2 if axis is not None or x.ndim == 1 else "fro"
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p)


def vector_norm(x, p=2, axis=None, keepdim=False):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


@op_fn
def matrix_norm(x, *, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)


@op_fn
def cholesky(x, *, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@op_fn
def inv(x):
    return jnp.linalg.inv(x)


@op_fn
def pinv(x, *, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


@op_fn
def solve(x, y):
    return jnp.linalg.solve(x, y)


@op_fn
def triangular_solve(x, y, *, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular)


@op_fn
def cholesky_solve(x, y, *, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@op_fn(differentiable=False)
def matrix_rank(x, *, tol=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@op_fn
def det(x):
    return jnp.linalg.det(x)


@op_fn
def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@op_fn
def matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


# qr/svd/eig/eigh/lu/lstsq live in linalg_ext.py (taped, reference-
# convention outputs — svd returns VH per tensor/linalg.py:2503).

@op_fn
def multi_dot_op(*xs):
    return jnp.linalg.multi_dot(xs, precision=_precision())


def multi_dot(xs):
    return multi_dot_op(*xs)


@op_fn
def matrix_exp(x):
    return jax.scipy.linalg.expm(x)
