"""Random sampling ops (paddle.tensor.random parity), keyed by the RNG
subsystem in framework/random.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..framework.random import next_key
from ._op import unwrap, wrap


def _dt(dtype):
    return dtypes.convert_dtype(dtype) if dtype is not None else dtypes.get_default_dtype()


def uniform(shape, dtype=None, min=-1.0, max=1.0):
    return wrap(jax.random.uniform(next_key(), tuple(shape), dtype=_dt(dtype),
                                   minval=min, maxval=max))


def rand(shape, dtype=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None):
    return wrap(jax.random.normal(next_key(), tuple(shape), dtype=_dt(dtype)))


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None):
    mean_, std_ = unwrap(mean), unwrap(std)
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(mean_), jnp.shape(std_))
    return wrap(mean_ + std_ * jax.random.normal(next_key(), tuple(shape),
                                                 dtype=dtypes.get_default_dtype()))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return wrap(jax.random.randint(next_key(), tuple(shape), low, high,
                                   dtype=dtypes.convert_dtype(dtype)))


def randperm(n, dtype="int64"):
    return wrap(jax.random.permutation(next_key(), n).astype(dtypes.convert_dtype(dtype)))


def bernoulli(x):
    p = unwrap(x)
    return wrap(jax.random.bernoulli(next_key(), p).astype(p.dtype))


def multinomial(x, num_samples=1, replacement=False):
    p = unwrap(x)
    logits = jnp.log(jnp.clip(p, 1e-30, None))
    if replacement:
        if logits.ndim == 1:
            out = jax.random.categorical(next_key(), logits, shape=(num_samples,))
        else:
            out = jax.random.categorical(next_key(), logits[..., None, :],
                                         shape=logits.shape[:-1] + (num_samples,))
        return wrap(out.astype(jnp.int64))
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(next_key(), logits.shape, dtype=logits.dtype)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return wrap(idx.astype(jnp.int64))


def poisson(x):
    lam = unwrap(x)
    return wrap(jax.random.poisson(next_key(), lam).astype(lam.dtype))


def exponential_(x, lam=1.0):
    sample = jax.random.exponential(next_key(), tuple(x.shape)) / lam
    x._data = sample.astype(x.dtype)
    return x


def shuffle(x, axis=0):
    return wrap(jax.random.permutation(next_key(), unwrap(x), axis=axis,
                                       independent=False))


def gumbel(shape, dtype=None):
    return wrap(jax.random.gumbel(next_key(), tuple(shape), dtype=_dt(dtype)))
