"""Reduction ops (paddle.tensor math/search reductions).

Reductions map onto XLA reduce ops that tile efficiently on the TPU VPU
(replacing paddle/phi/kernels/funcs/reduce_function.h machinery)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._op import op_fn


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


@op_fn(name="sum")
def sum(x, *, axis=None, keepdim=False, dtype=None):
    return jnp.sum(x, axis=_axis(axis), keepdims=keepdim, dtype=dtype)


@op_fn
def mean(x, *, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@op_fn(name="max")
def max(x, *, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@op_fn(name="min")
def min(x, *, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@op_fn
def prod(x, *, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_axis(axis), keepdims=keepdim, dtype=dtype)


@op_fn
def amax(x, *, axis=None, keepdim=False):
    return jnp.amax(x, axis=_axis(axis), keepdims=keepdim)


@op_fn
def amin(x, *, axis=None, keepdim=False):
    return jnp.amin(x, axis=_axis(axis), keepdims=keepdim)


@op_fn
def nansum(x, *, axis=None, keepdim=False):
    return jnp.nansum(x, axis=_axis(axis), keepdims=keepdim)


@op_fn
def nanmean(x, *, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@op_fn(name="all", differentiable=False)
def all(x, *, axis=None, keepdim=False):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@op_fn(name="any", differentiable=False)
def any(x, *, axis=None, keepdim=False):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@op_fn(differentiable=False)
def argmax(x, *, axis=None, keepdim=False, dtype="int64"):
    r = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return r


@op_fn(differentiable=False)
def argmin(x, *, axis=None, keepdim=False, dtype="int64"):
    return jnp.argmin(x, axis=axis, keepdims=keepdim)


@op_fn
def std(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@op_fn
def var(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@op_fn
def median(x, *, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


@op_fn
def quantile(x, q, *, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=_axis(axis), keepdims=keepdim)


@op_fn(differentiable=False)
def count_nonzero(x, *, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


@op_fn
def kthvalue_values(x, *, k, axis=-1, keepdim=False):
    v = jnp.sort(x, axis=axis)
    idx = k - 1
    taken = jnp.take(v, idx, axis=axis)
    if keepdim:
        taken = jnp.expand_dims(taken, axis)
    return taken
