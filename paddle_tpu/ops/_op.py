"""Op dispatcher: the eager "ad-function" layer.

TPU-native replacement for the reference's generated per-op forward wrappers
(paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:301
FORWARD_FUNCTION_TEMPLATE) and kernel dispatch
(paddle/phi/core/kernel_factory.cc:230 SelectKernelOrThrowError):

- every op is a *pure JAX function* over arrays (the single source of truth,
  like the reference's ops.yaml specs);
- the ``@op_fn`` decorator produces the user-facing eager function: unwrap
  Tensor handles, run the pure function (XLA dispatches to TPU), and — when
  grads are needed — record a GradNode whose backward is the ``jax.vjp``
  closure of the same pure function. No per-op grad code, no codegen step.
- under jit tracing ("functional mode") the tape is bypassed; the same pure
  functions trace into the compiled program.
"""
from __future__ import annotations

import functools
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..amp.auto_cast import _amp as _amp_state
from ..amp.auto_cast import current_cast_dtype_for as _current_cast_dtype_for
from ..core import state
from ..core.flags import flag_info, flag_value
from ..core.tensor import Tensor

# Monitor gate: cached flag record (set_flags mutates it in place) so
# the uninstrumented hot path pays one attribute load + branch. The
# recording helper imports lazily — paddle_tpu.monitor is cheap but
# this module loads very early in package init.
_MON_FLAG = flag_info("enable_monitor")
_MON_RECORD = None


def _monitor_record_op(opname, wall_ns):
    global _MON_RECORD
    if _MON_RECORD is None:
        from ..monitor import record_op as _MON_RECORD  # noqa: PLW0603
    _MON_RECORD(opname, wall_ns)

_OP_REGISTRY = {}

# (fn, diff_idx, arg-structure key) -> jitted backward. jax.jit's own
# cache keys the compiled executable by shapes/dtypes, so one entry here
# serves every shape the op runs at. Bounded: an op fed a NEW hashable
# scalar kwarg every step (annealed dropout p, per-step clip bound, ...)
# would otherwise leak one jitted backward per distinct value — at the
# cap the oldest entries (insertion order) are evicted, dropping their
# jit caches with them.
_BWD_CACHE: dict = {}
_BWD_CACHE_MAX = 2048


def _hashable(v):
    try:
        hash(v)
        return True
    except TypeError:
        return False


def _deferred_vjp(fn, raw, kwraw, diff_idx):
    """A vjp callable that does its tracing at BACKWARD time through a
    cached jitted function (steady-state: zero Python tracing per step).
    Splits kwargs / non-diff positionals into static (hashable, part of
    the cache key) and dynamic (arrays — e.g. RNG keys — passed as jit
    inputs). Falls back to a plain deferred jax.vjp when a static value
    isn't hashable."""
    diff_primals = tuple(raw[i] for i in diff_idx)
    dyn_kw = {k: v for k, v in kwraw.items()
              if isinstance(v, jax.Array)}
    static_kw = {k: v for k, v in kwraw.items() if k not in dyn_kw}
    nondiff = {i: a for i, a in enumerate(raw) if i not in diff_idx}
    dyn_nd = {i: a for i, a in nondiff.items()
              if isinstance(a, jax.Array)}
    static_nd = {i: a for i, a in nondiff.items() if i not in dyn_nd}
    n_args = len(raw)
    jittable = all(_hashable(v) for v in static_kw.values()) and \
        all(_hashable(v) for v in static_nd.values())

    if not jittable:
        def lazy(cts):
            def closed(*d):
                full = list(raw)
                for i, a in zip(diff_idx, d):
                    full[i] = a
                return fn(*full, **kwraw)
            return jax.vjp(closed, *diff_primals)[1](cts)
        return lazy

    key = (fn, tuple(diff_idx), n_args,
           tuple(sorted(static_kw.items(), key=lambda kv: kv[0])),
           tuple(sorted(static_nd.items())),
           tuple(sorted(dyn_kw)), tuple(sorted(dyn_nd)))
    bwd = _BWD_CACHE.get(key)
    if bwd is not None:
        # LRU refresh: a hit moves to the end so one op churning fresh
        # scalar kwargs evicts only its own stale keys, never the other
        # ops' stable hot backwards
        _BWD_CACHE.pop(key)
        _BWD_CACHE[key] = bwd
    if bwd is None:
        def bwd_impl(diff_primals, dyn_kw, dyn_nd, cts):
            def closed(*d):
                full = [None] * n_args
                for i, a in static_nd.items():
                    full[i] = a
                for i, a in dyn_nd.items():
                    full[i] = a
                for i, a in zip(diff_idx, d):
                    full[i] = a
                return fn(*full, **static_kw, **dyn_kw)
            return jax.vjp(closed, *diff_primals)[1](cts)
        bwd = jax.jit(bwd_impl)
        while len(_BWD_CACHE) >= _BWD_CACHE_MAX:
            _BWD_CACHE.pop(next(iter(_BWD_CACHE)))
        _BWD_CACHE[key] = bwd

    def lazy(cts):
        return bwd(diff_primals, dyn_kw, dyn_nd, cts)
    return lazy

# Profiler seam (reference: the RecordEvent wrapper in every generated
# ad-func, eager_gen.py). None when no profiler is recording — a single
# tuple-load guard on the hot path.
_PROFILE_HOOK = None


def set_profile_hook(begin, end):
    global _PROFILE_HOOK
    _PROFILE_HOOK = (begin, end) if begin is not None else None


# Active segmented-capture Program (jit/segment.py): while set, EVERY
# dispatched op records into it — including ops whose inputs are only
# Parameters or concrete tensors. Parameters encode as live _ParamRefs,
# so param-derived values stay fresh across weight updates in cached
# replays (and concretizing one creates a guard on its current value).
_SEGMENT_PROGRAM = None


def set_segment_program(prog):
    """Returns the previous value (caller restores it — recordings can
    nest)."""
    global _SEGMENT_PROGRAM
    prev = _SEGMENT_PROGRAM
    _SEGMENT_PROGRAM = prog
    return prev


# Flipped (permanently) by the first static.data() call — gates the
# symbolic-input scan off the eager hot path.
_HAS_SYMBOLIC = False


def enable_symbolic_scan():
    global _HAS_SYMBOLIC
    _HAS_SYMBOLIC = True


def unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_diff_dtype(dt) -> bool:
    # Only inexact dtypes participate in AD (int leaves would otherwise
    # produce jax float0 tangents).
    return jnp.issubdtype(dt, jnp.inexact)


def wrap(x, stop_gradient=True):
    return Tensor(x, stop_gradient=stop_gradient)


def _unwrap_index(idx):
    """Unwrap Tensors inside an indexing expression."""
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    return idx


def op_fn(fn: Callable = None, *, name: str = None, differentiable: bool = True,
          nondiff_args: tuple = ()):
    """Decorator turning a pure JAX function into an eager op.

    Convention: tensor inputs are positional; config is keyword-only.
    ``nondiff_args``: positional indices never differentiated (e.g. integer
    label inputs). Comparison/int-output ops pass ``differentiable=False``.
    """
    if fn is None:
        return functools.partial(op_fn, name=name, differentiable=differentiable,
                                 nondiff_args=nondiff_args)
    opname = name or fn.__name__

    @functools.wraps(fn)
    def dispatch(*args, **kwargs):
        ph = _PROFILE_HOOK
        if ph is None and not _MON_FLAG.value:
            return _dispatch_inner(*args, **kwargs)
        if ph is not None:
            ph[0](opname)
        t0 = time.perf_counter_ns() if _MON_FLAG.value else 0
        try:
            return _dispatch_inner(*args, **kwargs)
        finally:
            if t0:
                _monitor_record_op(opname, time.perf_counter_ns() - t0)
            if ph is not None:
                ph[1]()

    def _dispatch_inner(*args, **kwargs):
        # static-build interception (reference: under program_guard ops
        # append to the Program instead of executing — framework.py
        # in_dygraph_mode branch of every API). A symbolic input (positional
        # OR keyword) means we are inside a static.Program build. The scan
        # is gated on a flag flipped by the first static.data() call, so
        # purely-eager programs pay one global load per dispatch.
        if _SEGMENT_PROGRAM is not None:
            return _record_static(_SEGMENT_PROGRAM, opname, fn,
                                  args, kwargs)
        if _HAS_SYMBOLIC:
            for a in args:
                if isinstance(a, Tensor) and a._symbolic is not None:
                    return _record_static(a._symbolic.program, opname, fn,
                                          args, kwargs)
            for a in kwargs.values():
                if isinstance(a, Tensor) and a._symbolic is not None:
                    return _record_static(a._symbolic.program, opname, fn,
                                          args, kwargs)
        raw = [unwrap(a) for a in args]
        kwraw = {k: unwrap(v) for k, v in kwargs.items()}

        # AMP auto-cast seam (reference: the AMP_LOGIC_TEMPLATE block in every
        # generated ad-func, eager_gen.py:565): white-list ops cast float
        # inputs to the amp dtype, black-list ops to float32.
        amp_dt = _amp_state.enabled and _current_cast_dtype_for(opname)
        if amp_dt:
            raw = [a.astype(amp_dt)
                   if (hasattr(a, "dtype") and hasattr(a, "astype")
                       and jnp.issubdtype(a.dtype, jnp.floating)
                       and a.dtype != amp_dt)
                   else a for a in raw]

        need_grad = (
            differentiable
            and state.grad_enabled()
            and any(isinstance(a, Tensor) and not a.stop_gradient
                    and i not in nondiff_args
                    and _is_diff_dtype(a._data.dtype)
                    for i, a in enumerate(args))
        )

        if not need_grad:
            out = fn(*raw, **kwraw)
            if flag_value("check_nan_inf"):
                _check_nan_inf(opname, out)
            if isinstance(out, tuple):
                return tuple(wrap(o) for o in out)
            return wrap(out)

        diff_idx = [i for i, a in enumerate(args)
                    if isinstance(a, Tensor) and not a.stop_gradient
                    and i not in nondiff_args
                    and _is_diff_dtype(a._data.dtype)]
        diff_tensors = [args[i] for i in diff_idx]

        if flag_value("eager_jit_ops"):
            # Fast grad path (reference capability: the generated-C++
            # dygraph hot loop, eager_gen.py:301 — ours must not pay a
            # jax.vjp re-trace per op per step). Forward runs the plain
            # fn; the vjp is DEFERRED to backward and served by a jitted
            # function cached per (op, signature), so steady-state
            # training pays zero Python tracing in either direction.
            # Safe because fn is pure: randomness enters via key kwargs
            # captured in kwraw, so the backward's re-execution of the
            # forward (inside the cached vjp) reproduces it exactly.
            out = fn(*raw, **kwraw)
            vjp_fn = _deferred_vjp(fn, raw, kwraw, diff_idx)
        else:
            def closed(*diff_arrays):
                full = list(raw)
                for i, a in zip(diff_idx, diff_arrays):
                    full[i] = a
                return fn(*full, **kwraw)

            out, vjp_fn = jax.vjp(closed, *[raw[i] for i in diff_idx])
        if flag_value("check_nan_inf"):
            _check_nan_inf(opname, out)

        from ..autograd import tape
        # create_graph support: store what's needed to REBUILD the pure
        # call (fn + kwargs + non-diff raw args) rather than the `closed`
        # closure itself — the closure would pin every raw input for the
        # graph's lifetime, while the diff arrays are already retained via
        # node.inputs and are re-read from there at double-grad time.
        nondiff_raw = {i: a for i, a in enumerate(raw) if i not in diff_idx}
        pure_spec = (fn, kwraw, tuple(diff_idx), nondiff_raw, len(raw))
        if isinstance(out, tuple):
            outs = [wrap(o) for o in out]
            node = tape.record_node(opname, vjp_fn, diff_tensors, outs)
            node.pure_spec, node.multi_out = pure_spec, True
            return tuple(outs)
        out_t = wrap(out)
        node = tape.record_node(opname, vjp_fn, diff_tensors, [out_t])
        node.pure_spec, node.multi_out = pure_spec, False
        return out_t

    dispatch.pure_fn = fn
    dispatch.op_name = opname
    _OP_REGISTRY[opname] = dispatch
    return dispatch


def get_op(name: str):
    return _OP_REGISTRY.get(name)


def registered_ops():
    return dict(_OP_REGISTRY)


def _record_static(prog, opname, fn, args, kwargs):
    """Record one op into a static Program (static/ir.py) with output
    shapes from jax.eval_shape — the InferMeta step of the reference's
    static op append (SURVEY §2.1). Tensor kwargs (symbolic or concrete)
    are traced; non-tensor config kwargs are baked."""
    spec_args = [a._data if isinstance(a, Tensor) else a for a in args]
    tensor_kw = {k: v._data for k, v in kwargs.items()
                 if isinstance(v, Tensor)}
    static_kw = {k: v for k, v in kwargs.items()
                 if not isinstance(v, Tensor)}
    out = jax.eval_shape(lambda *xs, **tkw: fn(*xs, **static_kw, **tkw),
                         *spec_args, **tensor_kw)
    multi = isinstance(out, tuple)
    outs = out if multi else (out,)
    out_tensors = prog.record_op(opname, fn, list(args), dict(kwargs), outs)
    return tuple(out_tensors) if multi else out_tensors[0]


def _check_nan_inf(opname, out):
    outs = out if isinstance(out, tuple) else (out,)
    for o in outs:
        if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.floating):
            bad = bool(jnp.any(~jnp.isfinite(o)))
            if bad:
                raise FloatingPointError(f"NaN/Inf detected in output of op '{opname}'")
