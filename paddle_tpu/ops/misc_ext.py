"""Remaining top-level tensor API parity: stack variants, combinations,
pdist, *_like random, binomial/standard_gamma sampling.

Reference capability: python/paddle/tensor/manipulation.py (hstack/vstack/
dstack/column_stack/row_stack), math.py (combinations, pdist),
random.py (randint_like, binomial, standard_gamma).
TPU-native: jnp compositions; sampling via jax.random with the global
framework key chain.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.random import next_key
from ._op import op_fn, unwrap, wrap

__all__ = [
    "hstack", "vstack", "dstack", "column_stack", "row_stack",
    "combinations", "pdist", "randint_like", "binomial", "standard_gamma",
]


def _seq(xs):
    return [unwrap(x) for x in xs]


def hstack(x, name=None):
    return wrap(jnp.hstack(_seq(x)))


def vstack(x, name=None):
    return wrap(jnp.vstack(_seq(x)))


row_stack = vstack


def dstack(x, name=None):
    return wrap(jnp.dstack(_seq(x)))


def column_stack(x, name=None):
    return wrap(jnp.column_stack(_seq(x)))


@op_fn(differentiable=False)
def _combinations(x, *, r=2, with_replacement=False):
    """All r-combinations of the elements of 1-D ``x`` — [C, r].

    Index tuples are enumerated host-side from the static length (the
    combinatorial structure is shape-only), then gathered on device.
    """
    import itertools

    n = x.shape[0]
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    tuples = list(gen(range(n), r))
    if not tuples:
        return jnp.zeros((0, r), x.dtype)
    return x[jnp.asarray(tuples, jnp.int32)]


def combinations(x, r=2, with_replacement=False, name=None):
    return _combinations(x, r=int(r), with_replacement=bool(with_replacement))


@op_fn
def pdist(x, p=2.0):
    """Condensed pairwise distance of [N, D] rows — [N*(N-1)/2]."""
    n = x.shape[0]
    iu, ju = jnp.triu_indices(n, k=1)
    diff = x[iu] - x[ju]
    if p == 2.0:
        # sqrt of clamped sumsq: grad-safe at 0 and MXU-friendly
        return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=-1), 1e-24))
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    xa = unwrap(x)
    if high is None:
        low, high = 0, low
    from ..core.dtype import convert_dtype
    dt = convert_dtype(dtype) if dtype is not None else xa.dtype
    out = jax.random.randint(next_key(), xa.shape, int(low), int(high))
    return wrap(out.astype(dt))


def binomial(count, prob, name=None):
    """Sample Binomial(count, prob) elementwise (reference: random.py
    binomial). Uses jax.random.binomial (Stirling/inversion on device)."""
    c = unwrap(count).astype(jnp.float32)
    pr = unwrap(prob).astype(jnp.float32)
    out = jax.random.binomial(next_key(), c, pr)
    return wrap(out.astype(jax.dtypes.canonicalize_dtype(jnp.int64)))


def standard_gamma(x, name=None):
    """Sample Gamma(alpha=x, scale=1) elementwise (reference: random.py
    standard_gamma)."""
    xa = unwrap(x)
    return wrap(jax.random.gamma(next_key(), xa))
