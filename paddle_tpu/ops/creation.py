"""Creation ops (paddle.tensor.creation parity, python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor, to_tensor  # noqa: F401  (re-exported)
from ._op import op_fn, unwrap, wrap


def _dt(dtype):
    return dtypes.convert_dtype(dtype) if dtype is not None else dtypes.get_default_dtype()


def zeros(shape, dtype=None):
    return wrap(jnp.zeros(shape, _dt(dtype)))


def ones(shape, dtype=None):
    return wrap(jnp.ones(shape, _dt(dtype)))


def full(shape, fill_value, dtype=None):
    fill_value = unwrap(fill_value)
    return wrap(jnp.full(shape, fill_value, _dt(dtype)))


def empty(shape, dtype=None):
    return wrap(jnp.zeros(shape, _dt(dtype)))


@op_fn
def zeros_like(x, *, dtype=None):
    return jnp.zeros_like(x, dtype=dtypes.convert_dtype(dtype) if dtype else None)


@op_fn
def ones_like(x, *, dtype=None):
    return jnp.ones_like(x, dtype=dtypes.convert_dtype(dtype) if dtype else None)


@op_fn
def full_like(x, fill_value, *, dtype=None):
    return jnp.full_like(x, fill_value, dtype=dtypes.convert_dtype(dtype) if dtype else None)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype=dtype)


def arange(start=0, end=None, step=1, dtype=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = dtypes.convert_dtype("int64")  # canonicalizes per x64 mode
        else:
            dtype = dtypes.get_default_dtype()
    else:
        dtype = dtypes.convert_dtype(dtype)
    return wrap(jnp.arange(start, end, step, dtype=dtype))


def linspace(start, stop, num, dtype=None):
    return wrap(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                             dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return wrap(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                             base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return wrap(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


@op_fn
def clone(x):
    # Arrays are immutable; a differentiable identity is a true clone.
    return jnp.asarray(x)


def assign(x, output=None):
    """paddle.assign parity: copy into `output` if given."""
    x = x if isinstance(x, Tensor) else to_tensor(x)
    if output is None:
        return clone(x)
    output.set_value(x)
    return output


@op_fn
def diag(x, *, offset=0):
    return jnp.diag(x, k=offset)


@op_fn
def diagflat(x, *, offset=0):
    return jnp.diagflat(x, k=offset)


@op_fn
def tril(x, *, diagonal=0):
    return jnp.tril(x, k=diagonal)


@op_fn
def triu(x, *, diagonal=0):
    return jnp.triu(x, k=diagonal)


def meshgrid(*args, indexing="ij"):
    arrays = [unwrap(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return tuple(wrap(g) for g in jnp.meshgrid(*arrays, indexing=indexing))


def tril_indices(row, col, offset=0):
    r, c = jnp.tril_indices(row, offset, col)
    return wrap(jnp.stack([r, c]))


def triu_indices(row, col, offset=0):
    r, c = jnp.triu_indices(row, offset, col)
    return wrap(jnp.stack([r, c]))


def complex(real, imag):
    return wrap(jnp.asarray(unwrap(real)) + 1j * jnp.asarray(unwrap(imag)))
