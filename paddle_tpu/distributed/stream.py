"""paddle.distributed.stream — stream-variant collectives.

Reference capability: python/paddle/distributed/communication/stream/ —
the same collectives with ``use_calc_stream`` control (run on the
compute stream instead of the comm stream, skipping the event sync).

TPU-native reality: XLA schedules collectives and compute on the same
program timeline (there is no user-visible stream pair to choose
between, recorded in docs/CAPABILITY_DELTA.md §streams), so each stream
op is the corresponding collective with the extra argument accepted.
"""
from __future__ import annotations

from . import collective as _c

__all__ = ["all_gather", "all_reduce", "alltoall", "alltoall_single",
           "broadcast", "gather", "reduce", "reduce_scatter", "recv",
           "scatter", "send"]


def _wrap(fn):
    import functools

    @functools.wraps(fn)
    def op(*args, sync_op=True, use_calc_stream=False, **kwargs):
        kwargs.pop("use_calc_stream", None)
        return fn(*args, **kwargs)
    return op


all_gather = _wrap(_c.all_gather)
all_reduce = _wrap(_c.all_reduce)
alltoall = _wrap(_c.alltoall)
alltoall_single = _wrap(_c.alltoall_single)
broadcast = _wrap(_c.broadcast)
gather = _wrap(_c.gather)
reduce = _wrap(_c.reduce)
reduce_scatter = _wrap(_c.reduce_scatter)
recv = _wrap(_c.recv)
scatter = _wrap(_c.scatter)
send = _wrap(_c.send)
