"""Semi-auto parallel API: shard_tensor / reshard / shard_layer /
shard_optimizer / ShardingStage1-3 / shard_dataloader.

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor:131,
reshard:579, shard_layer:678, shard_optimizer:1353, ShardingStage1/2/3
shard_fns :1122-1352, shard_dataloader:2846).

TPU-native redesign (SURVEY.md §7): ``jax.Array + NamedSharding`` *is* the
DistTensor. ``shard_tensor`` = ``jax.device_put`` with a NamedSharding;
``reshard`` = another device_put — XLA emits the collective (all-gather,
all-to-all for s→s, etc.) over ICI. SPMD propagation (the reference's 85
spmd_rules files) comes free from GSPMD: ops on sharded arrays produce
correctly-sharded outputs with compiler-inserted collectives.

On Partial: jax.Array presents *global-value semantics* — a pending partial
sum is compiler-internal (GSPMD partial tiles), never user-visible state.
We accept Partial placements for API parity, record them as annotations, and
store the materialized (already-reduced) value; resharding Partial→Replicate
is therefore a data no-op. This is a deliberate semantic upgrade, not a gap:
the reference needs explicit p_to_r reshard functions because each rank holds
local partial state; a single-controller sharded array never does.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from ..core.tensor import Parameter, Tensor
from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh
from ..core import enforce as E

__all__ = [
    "shard_tensor", "reshard", "dtensor_from_fn", "unshard_dtensor",
    "shard_layer", "shard_optimizer", "shard_scaler", "shard_dataloader",
    "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "per_device_bytes",
]


def _storage_placements(placements: Sequence[Placement]) -> List[Placement]:
    """Partial stores replicated (see module docstring)."""
    return [Replicate() if isinstance(p, Partial) else p for p in placements]


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Distribute ``data`` over ``mesh`` per ``placements``.

    Reference: auto_parallel/api.py:131 shard_tensor (creates DistTensor with
    TensorDistAttr). Here: device_put with NamedSharding; annotation kept on
    the handle for introspection parity (Tensor.placements/.process_mesh).
    """
    from ..core.tensor import to_tensor
    t = data if isinstance(data, Tensor) else to_tensor(data, dtype=dtype)
    sharding = mesh.named_sharding(_storage_placements(placements))
    arr = jax.device_put(t._data, sharding)
    if isinstance(t, Parameter):
        out = Parameter(arr, name=t.name, trainable=not t.stop_gradient)
    else:
        out = Tensor(arr, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient, name=t.name)
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    out._placements = list(placements)
    out._process_mesh = mesh
    return out


def reshard(tensor: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """Convert a tensor's distribution (reference: api.py:579 reshard; the
    C++ reshard function matrix r↔s/p↔r/s↔s is replaced by one device_put —
    XLA lowers s→s to all-to-all, s→r to all-gather, etc.)."""
    return shard_tensor(tensor, mesh, placements)


def dtensor_from_fn(fn: Callable, mesh: ProcessMesh,
                    placements: Sequence[Placement], *args, **kwargs) -> Tensor:
    """Reference: api.py dtensor_from_fn — build then distribute.

    TPU note: for large params, prefer constructing under jit with output
    shardings so each shard materializes directly on its device; here we
    build globally then device_put (fine at test scale, and jit paths in
    models use sharded init)."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def unshard_dtensor(tensor: Tensor) -> Tensor:
    """Gather to a fully-replicated plain tensor (api.py unshard_dtensor)."""
    if tensor._process_mesh is None:
        return tensor
    mesh = tensor._process_mesh
    rep = [Replicate() for _ in range(mesh.ndim)]
    arr = jax.device_put(tensor._data, mesh.named_sharding(rep))
    out = Tensor(arr, stop_gradient=tensor.stop_gradient, name=tensor.name)
    return out


def shard_layer(layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None):
    """Shard every parameter of ``layer`` in place.

    Reference: api.py:678 shard_layer. ``shard_fn(name, layer, mesh)``
    mutates one sublayer's params; default replicates everything (matching
    the reference's default)."""
    def _default(name, sublayer, mesh):
        for pname, p in list(sublayer._parameters.items()):
            if p is None:
                continue
            rep = [Replicate() for _ in range(mesh.ndim)]
            sublayer._parameters[pname] = _as_param(
                shard_tensor(p, mesh, rep))

    fn = shard_fn or _default
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def _as_param(t: Tensor) -> Parameter:
    if isinstance(t, Parameter):
        return t
    p = Parameter(t._data, name=t.name, trainable=not t.stop_gradient)
    p._placements = t._placements
    p._process_mesh = t._process_mesh
    return p


# -- sharding stages (ZeRO) -------------------------------------------------

class _ShardingStage:
    """Callable shard_fn passed to shard_optimizer.

    Reference: auto_parallel/api.py:1122-1352 (ShardingStage1/2/3 classes).
    TPU-native meaning on one Mesh:
      stage 1: optimizer states sharded over the sharding axis;
      stage 2: + gradients stored reduce-scattered over that axis;
      stage 3: + parameters sharded over that axis (gathered on use — in
               compiled steps XLA's GSPMD does gather-on-use from the
               sharding constraint; no hook machinery needed).
    """
    stage = 0

    def __init__(self, mesh_dim: str = "dp", mesh: Optional[ProcessMesh] = None):
        self.mesh_dim = mesh_dim
        self.mesh = mesh

    def _mesh(self) -> ProcessMesh:
        from .process_mesh import get_mesh
        mesh = self.mesh or get_mesh()
        if mesh is None:
            raise E.PreconditionNotMetError(
                "ShardingStage needs a mesh: pass one or dist.set_mesh(...)")
        return mesh

    def _shard_1d(self, t: Tensor) -> Tensor:
        """Shard dim 0 over the sharding axis when divisible, else replicate
        (reference behavior: non-divisible params stay unsharded)."""
        mesh = self._mesh()
        axis = mesh.dim_names.index(self.mesh_dim)
        n = mesh.shape[axis]
        placements: List[Placement] = [Replicate()] * mesh.ndim
        if t.ndim >= 1 and t.shape[0] % n == 0:
            placements[axis] = Shard(0)
        return shard_tensor(t, mesh, placements)

    def shard_accumulator(self, t: Tensor) -> Tensor:
        return self._shard_1d(t)

    def shard_gradient(self, t: Tensor) -> Tensor:
        if self.stage >= 2:
            return self._shard_1d(t)
        return t

    def shard_param(self, t: Tensor) -> Tensor:
        if self.stage >= 3:
            return self._shard_1d(t)
        return t


class ShardingStage1(_ShardingStage):
    stage = 1


class ShardingStage2(_ShardingStage):
    stage = 2


class ShardingStage3(_ShardingStage):
    stage = 3


class _ShardOptimizer:
    """Optimizer wrapper applying a sharding stage.

    Reference: api.py shard_optimizer/_ShardOptimizer. Accumulators are
    sharded at creation (stage1+); gradients reshard before step (stage2+);
    params live sharded (stage3). The wrapped optimizer's math is unchanged —
    XLA executes each update on the shards that own them.
    """

    def __init__(self, optimizer, shard_fn: Optional[_ShardingStage] = None):
        self._inner = optimizer
        self._shard_fn = shard_fn

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _shard_array(self, arr):
        """Shard a raw jax array's dim 0 over the sharding axis (device_put
        is a no-op when already placed)."""
        fn = self._shard_fn
        mesh = fn._mesh()
        axis = mesh.dim_names.index(fn.mesh_dim)
        n = mesh.shape[axis]
        if getattr(arr, "ndim", 0) < 1 or arr.shape[0] % n != 0:
            return arr
        placements: List[Placement] = [Replicate()] * mesh.ndim
        placements[axis] = Shard(0)
        return jax.device_put(
            arr, mesh.named_sharding(placements))

    def _place_grads_and_params(self):
        """Pre-step placement: stage>=2 shards grads, stage 3 params."""
        fn = self._shard_fn
        params = self._inner._parameter_list or []
        if fn.stage >= 2:
            for p in params:
                if getattr(p, "grad", None) is not None:
                    p.grad = fn.shard_gradient(p.grad)
        if fn.stage >= 3:
            for p in params:
                sharded = fn.shard_param(p)
                p._data = sharded._data
                p._placements = sharded._placements
                p._process_mesh = sharded._process_mesh

    def _place_accumulators(self):
        """Post-step placement: accumulators are created lazily during
        step(), so their sharding can only be applied after it. The inner
        dicts map state name -> raw jax array (optimizer.py _init_state)."""
        for acc_map in getattr(self._inner, "_accumulators", {}).values():
            for key, acc in list(acc_map.items()):
                if isinstance(acc, jax.Array):
                    acc_map[key] = self._shard_array(acc)

    def _apply_stage(self):
        if self._shard_fn is None:
            return
        self._place_grads_and_params()
        self._place_accumulators()

    def step(self):
        if self._shard_fn is not None and self._shard_fn.stage >= 2:
            self._place_grads_and_params()
        self._inner.step()
        if self._shard_fn is not None:
            self._place_accumulators()

    def clear_grad(self, set_to_zero: bool = False):
        self._inner.clear_grad(set_to_zero)


def shard_optimizer(optimizer, shard_fn: Optional[_ShardingStage] = None):
    """Reference: api.py:1353 shard_optimizer."""
    return _ShardOptimizer(optimizer, shard_fn)


def shard_scaler(scaler):
    """Reference: api.py shard_scaler — grad-scaler found/inf state is a
    global-semantics scalar here, nothing to do."""
    return scaler


class _ShardDataloader:
    """Wraps a DataLoader so each batch lands sharded over the dp axis.

    Reference: api.py:2846 shard_dataloader (DistributedDataLoader). Here:
    device_put the host batch with Shard(0) on ``shard_dims`` — in
    multi-process mode each host feeds its slice (jax makes the global array
    from per-host shards)."""

    def __init__(self, dataloader, meshes, shard_dims=None, input_keys=None):
        self._loader = dataloader
        self.mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes
        self.shard_dims = shard_dims if shard_dims is not None \
            else self.mesh.dim_names[0]
        self.input_keys = input_keys

    def __len__(self):
        return len(self._loader)

    def _shard_batch(self, item):
        mesh = self.mesh
        axis = mesh.dim_names.index(self.shard_dims) \
            if isinstance(self.shard_dims, str) else self.shard_dims
        placements: List[Placement] = [Replicate()] * mesh.ndim
        placements[axis] = Shard(0)

        def one(x):
            if isinstance(x, Tensor):
                return shard_tensor(x, mesh, placements)
            return x
        if isinstance(item, (list, tuple)):
            return type(item)(one(x) for x in item)
        if isinstance(item, dict):
            return {k: one(v) for k, v in item.items()}
        return one(item)

    def __iter__(self):
        for item in self._loader:
            yield self._shard_batch(item)


def shard_dataloader(dataloader, meshes, shard_dims=None, input_keys=None):
    return _ShardDataloader(dataloader, meshes, shard_dims, input_keys)


def per_device_bytes(tensors) -> dict:
    """Live-array memory accounting: bytes each device actually stores for
    ``tensors`` (replicated arrays count fully on every device; sharded
    arrays count only the local shard). The evidence function for ZeRO
    placement claims — reference capability: the memory reporting used by
    group_sharded tests (group_sharded_stage3.py peak-memory checks)."""
    out: dict = {}
    for t in tensors:
        arr = t._data if isinstance(t, Tensor) else t
        if not isinstance(arr, jax.Array):
            continue
        for shard in arr.addressable_shards:
            d = shard.device
            out[d] = out.get(d, 0) + int(np.prod(shard.data.shape)
                                         * shard.data.dtype.itemsize)
    return out
