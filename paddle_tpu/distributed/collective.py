"""Process groups + eager collective API.

Reference: paddle/fluid/distributed/collective/process_group.h:47
(ProcessGroup async API) + python/paddle/distributed/communication/
(all_reduce, all_gather, ... sync wrappers) + collective.py:186 new_group.

TPU-native redesign (SURVEY.md §2.5 "TPU-native equivalent note"): tensor
collectives are *compiled* — expressed as lax.psum/all_gather/... inside
jit/shard_map and lowered by XLA onto ICI (see comm_ops.py). The eager API
here serves the reference's *host-side* uses: barriers, object exchange,
checkpoint coordination, and world_size==1 parity semantics. Under a
single-controller runtime, an eager collective over a sharded jax.Array is
definitionally the identity on the global value (the array already has
global semantics); with multiple hosts, object collectives ride the
jax.distributed coordination service (client KV store), mirroring the
reference's TCPStore-based bootstrap (phi/core/distributed/store/tcp_store.cc).
"""
from __future__ import annotations

import pickle
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from ..core.tensor import Tensor, _nbytes_of
from ..testing import faults as _faults
from . import env
from ..core import enforce as E


def _note_eager(op: str, tensor=None):
    """Monitor-gated accounting for the eager (host-side) collectives —
    unlike comm_ops these count per CALL, not per trace."""
    if not _monitor.enabled():
        return
    _monitor.inc(f"dist.eager.{op}.calls",
                 doc="eager host-collective calls")
    if isinstance(tensor, Tensor):
        nbytes = _nbytes_of(tensor._data)
        if nbytes:
            _monitor.inc(f"dist.eager.{op}.bytes", nbytes,
                         doc="eager host-collective operand bytes")


def _lat(kind: str):
    """Wall-time context for the host exchanges that genuinely block
    (KV-store object gathers, barriers): observes
    ``comm.latency.<kind>_ms`` on the shared SLO buckets. A rank whose
    peers are slow shows up as a fat tail here — the fleet divergence
    report (monitor/fleet.py) surfaces exactly that."""
    from ..monitor.registry import LATENCY_BUCKETS_MS
    return _monitor.timed(
        f"comm.latency.{kind}_ms",
        doc="wall time of one eager/host collective of this kind",
        buckets=LATENCY_BUCKETS_MS)

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "destroy_process_group",
    "get_backend", "is_available", "all_reduce", "all_gather",
    "all_gather_object", "broadcast", "broadcast_object_list", "reduce",
    "scatter", "scatter_object_list", "gather", "alltoall",
    "alltoall_single", "reduce_scatter", "send", "recv", "isend", "irecv",
    "barrier", "wait",
]


class ReduceOp:
    """Reference: python/paddle/distributed/communication/reduce_op.py."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_REDUCE_FNS = {
    ReduceOp.SUM: jnp.add,
    ReduceOp.MAX: jnp.maximum,
    ReduceOp.MIN: jnp.minimum,
    ReduceOp.PROD: jnp.multiply,
}


class Group:
    """A communicator group (reference: communication/group.py Group).

    Ranks index the global (host-)process world. In the compiled path a
    group corresponds to a mesh axis; ``mesh_axis`` records that binding when
    the group was created from fleet topology (fleet/topology.py)."""

    def __init__(self, rank_in_group: int, gid: int, ranks: List[int],
                 mesh_axis: Optional[str] = None):
        self.rank = rank_in_group
        self.id = gid
        self.ranks = list(ranks)
        self.mesh_axis = mesh_axis

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self) -> bool:
        return env.get_rank() in self.ranks

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_group_map = {}
_next_gid = [1]
_default_group: Optional[Group] = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        world = list(range(env.get_world_size()))
        _default_group = Group(env.get_rank(), 0, world)
        _group_map[0] = _default_group
    return _default_group


def new_group(ranks: Optional[Sequence[int]] = None, backend=None,
              timeout=None, mesh_axis: Optional[str] = None) -> Group:
    """Reference: collective.py:186 new_group. Backend is always the XLA
    collective stack here (``backend`` accepted for parity)."""
    if ranks is None:
        ranks = list(range(env.get_world_size()))
    gid = _next_gid[0]
    _next_gid[0] += 1
    me = env.get_rank()
    rank_in_group = list(ranks).index(me) if me in ranks else -1
    g = Group(rank_in_group, gid, list(ranks), mesh_axis=mesh_axis)
    _group_map[gid] = g
    return g


def get_group(gid: int = 0) -> Optional[Group]:
    if gid == 0:
        return _get_default_group()
    return _group_map.get(gid)


def destroy_process_group(group: Optional[Group] = None):
    global _default_group
    if group is None:
        _group_map.clear()
        _default_group = None
        _next_gid[0] = 1
    else:
        _group_map.pop(group.id, None)


def get_backend(group: Optional[Group] = None) -> str:
    return "xla"


def is_available() -> bool:
    return True


def _group_size(group) -> int:
    return (group or _get_default_group()).nranks


def wait(tensor: Tensor, group=None, use_calc_stream: bool = True):
    """Async-task wait (reference ProcessGroup::Task::Wait). jax.Array
    dispatch is async already; block explicitly."""
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._data)
    return tensor


class _Task:
    """Completed-task handle for isend/irecv/async_op parity (the reference
    returns event-backed tasks; XLA dispatch is async by construction)."""

    def __init__(self, tensor=None):
        self._tensor = tensor

    def wait(self):
        if self._tensor is not None:
            wait(self._tensor)
        return True

    def is_completed(self):
        return True


# -- tensor collectives (eager; see module docstring for semantics) ---------

def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Global-semantics identity for n=1-per-process arrays; AVG divides.

    The hot-path allreduce (DP gradient sync) is NOT this function — it's
    lax.psum inside the compiled train step (comm_ops.all_reduce), or
    implicit from GSPMD when grads carry a dp-sharded batch dim."""
    _note_eager("all_reduce", tensor)
    n = _group_size(group)
    if n > 1 and op == ReduceOp.AVG:
        # Single-controller: array value is already the global sum-of-parts
        # only when each process contributed; with one controller there is
        # exactly one logical value, so SUM/MAX/MIN/PROD are identities.
        pass
    return _Task(tensor) if not sync_op else tensor


def all_gather(tensor_list: List[Tensor], tensor: Tensor, group=None,
               sync_op=True):
    _note_eager("all_gather", tensor)
    n = _group_size(group)
    tensor_list.clear()
    tensor_list.extend(Tensor(tensor._data) for _ in range(n))
    return _Task() if not sync_op else None


# Per-process call counter for coordination-service keys. Collective calls
# execute in the same order on every process (SPMD single-controller-per-host
# discipline), so the counter value is identical across peers at each call —
# unlike id(object_list), which is process-local.
_AG_SEQ = [0]


def all_gather_object(object_list: List, obj, group=None, tag=None):
    """Host object exchange. Multi-host: via the coordination-service KV
    store (jax.distributed client), mirroring TCPStore exchange.

    Untagged calls pair across hosts by a per-process sequence counter,
    which is only sound when every host issues its collectives in the
    same order from ONE thread. Callers running off the main thread
    (e.g. the async checkpoint writer) must pass an explicit ``tag``
    that is identical across hosts and unique per exchange — tagged
    rounds use their own KV keys and cannot mis-pair with the counter."""
    _faults.hit("collective.gather")
    _note_eager("all_gather_object")
    n = _group_size(group)
    client = _coord_client()
    with _lat("all_gather_object"):
        if client is not None and n > 1:
            if tag is None:
                tag = _AG_SEQ[0]
                _AG_SEQ[0] += 1
            me = env.get_rank()
            blob = pickle.dumps(obj).hex()
            client.key_value_set(f"ag_{tag}_{me}", blob)
            object_list.clear()
            for r in range(n):
                data = client.blocking_key_value_get(f"ag_{tag}_{r}",
                                                     60_000)
                object_list.append(pickle.loads(bytes.fromhex(data)))
        else:
            object_list.clear()
            object_list.extend(obj for _ in range(n))


def _coord_client():
    try:
        from jax._src import distributed as _dist
        state = _dist.global_state
        return state.client if state.client is not None else None
    except Exception:
        return None


def broadcast(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    _note_eager("broadcast", tensor)
    return _Task(tensor) if not sync_op else tensor


def broadcast_object_list(object_list: List, src: int = 0, group=None):
    return object_list


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group=None,
           sync_op=True):
    return _Task(tensor) if not sync_op else tensor


def scatter(tensor: Tensor, tensor_list=None, src: int = 0, group=None,
            sync_op=True):
    if tensor_list:
        me = (group or _get_default_group()).rank
        me = max(me, 0)
        tensor._data = tensor_list[me]._data
    return _Task(tensor) if not sync_op else tensor


def scatter_object_list(out_object_list: List, in_object_list=None,
                        src: int = 0, group=None):
    me = (group or _get_default_group()).rank
    me = max(me, 0)
    out_object_list.clear()
    if in_object_list:
        out_object_list.append(in_object_list[me])


def gather(tensor: Tensor, gather_list=None, dst: int = 0, group=None,
           sync_op=True):
    n = _group_size(group)
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend(Tensor(tensor._data) for _ in range(n))
    return _Task() if not sync_op else None


def alltoall(out_tensor_list: List[Tensor], in_tensor_list: List[Tensor],
             group=None, sync_op=True):
    out_tensor_list.clear()
    out_tensor_list.extend(Tensor(t._data) for t in in_tensor_list)
    return _Task() if not sync_op else None


def alltoall_single(out_tensor: Tensor, in_tensor: Tensor,
                    in_split_sizes=None, out_split_sizes=None, group=None,
                    sync_op=True):
    out_tensor._data = in_tensor._data
    return _Task(out_tensor) if not sync_op else out_tensor


def reduce_scatter(tensor: Tensor, tensor_list: List[Tensor],
                   op=ReduceOp.SUM, group=None, sync_op=True):
    me = (group or _get_default_group()).rank
    me = max(me, 0)
    tensor._data = tensor_list[me]._data
    return _Task(tensor) if not sync_op else tensor


def send(tensor: Tensor, dst: int = 0, group=None, sync_op=True):
    """P2P in the compiled path is lax.ppermute (comm_ops.p2p_permute);
    eager host send between controller processes is not a supported TPU
    pattern — accept for API parity in world-size-1."""
    if _group_size(group) > 1 and env.get_world_size() > 1:
        raise NotImplementedError(
            "eager host-to-host send is not supported; use the compiled "
            "p2p path (paddle_tpu.distributed.comm_ops.p2p_permute) or "
            "object collectives")
    return _Task(tensor) if not sync_op else tensor


def recv(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    if _group_size(group) > 1 and env.get_world_size() > 1:
        raise NotImplementedError(
            "eager host-to-host recv is not supported; use the compiled "
            "p2p path (paddle_tpu.distributed.comm_ops.p2p_permute)")
    return _Task(tensor) if not sync_op else tensor


def isend(tensor: Tensor, dst: int = 0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor: Tensor, src: int = 0, group=None):
    return recv(tensor, src, group, sync_op=False)


def barrier(group=None):
    """Host barrier over the coordination service (reference: TCPStore
    barrier / ProcessGroup barrier)."""
    _note_eager("barrier")
    client = _coord_client()
    with _lat("barrier"):
        if client is not None and env.get_world_size() > 1:
            client.wait_at_barrier("pt_barrier", 60_000)
        else:
            (jnp.zeros(()) + 0).block_until_ready()



class P2POp:
    """A deferred point-to-point op for batch_isend_irecv (reference:
    distributed/communication/batch_isend_irecv.py P2POp): op is
    paddle.distributed.isend or irecv."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv):
            raise E.InvalidArgumentError(
                "P2POp.op must be paddle.distributed.isend or irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Execute a batch of P2POps, returning their tasks (reference:
    batch_isend_irecv.py). Identity-semantics single-process groups
    complete immediately; multi-process p2p rides the same KV-store
    exchange send/recv use."""
    if not p2p_op_list:
        raise E.InvalidArgumentError("p2p_op_list must not be empty")
    if not all(isinstance(p, P2POp) for p in p2p_op_list):
        raise E.InvalidArgumentError("p2p_op_list must contain only P2POp")
    return [p.op(p.tensor, p.peer, group=p.group) for p in p2p_op_list]
