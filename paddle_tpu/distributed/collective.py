"""Process groups + eager collective API.

Reference: paddle/fluid/distributed/collective/process_group.h:47
(ProcessGroup async API) + python/paddle/distributed/communication/
(all_reduce, all_gather, ... sync wrappers) + collective.py:186 new_group.

TPU-native redesign (SURVEY.md §2.5 "TPU-native equivalent note"): tensor
collectives are *compiled* — expressed as lax.psum/all_gather/... inside
jit/shard_map and lowered by XLA onto ICI (see comm_ops.py). The eager API
here serves the reference's *host-side* uses: barriers, object exchange,
checkpoint coordination, and world_size==1 parity semantics. Under a
single-controller runtime, an eager collective over a sharded jax.Array is
definitionally the identity on the global value (the array already has
global semantics); with multiple hosts, object collectives ride the
jax.distributed coordination service (client KV store), mirroring the
reference's TCPStore-based bootstrap (phi/core/distributed/store/tcp_store.cc).
"""
from __future__ import annotations

import os
import pickle
import sys
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from ..core.tensor import Tensor, _nbytes_of
from ..testing import faults as _faults
from . import env
from ..core import enforce as E
from .launch.main import (COLLECTIVE_TIMEOUT_RC,  # noqa: F401 (re-exported)
                          PEER_FAILURE_RC)


def _note_eager(op: str, tensor=None):
    """Monitor-gated accounting for the eager (host-side) collectives —
    unlike comm_ops these count per CALL, not per trace."""
    if not _monitor.enabled():
        return
    _monitor.inc(f"dist.eager.{op}.calls",
                 doc="eager host-collective calls")
    if isinstance(tensor, Tensor):
        nbytes = _nbytes_of(tensor._data)
        if nbytes:
            _monitor.inc(f"dist.eager.{op}.bytes", nbytes,
                         doc="eager host-collective operand bytes")


def _lat(kind: str):
    """Wall-time context for the host exchanges that genuinely block
    (KV-store object gathers, barriers): observes
    ``comm.latency.<kind>_ms`` on the shared SLO buckets. A rank whose
    peers are slow shows up as a fat tail here — the fleet divergence
    report (monitor/fleet.py) surfaces exactly that."""
    from ..monitor.registry import LATENCY_BUCKETS_MS
    return _monitor.timed(
        f"comm.latency.{kind}_ms",
        doc="wall time of one eager/host collective of this kind",
        buckets=LATENCY_BUCKETS_MS)

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "destroy_process_group",
    "get_backend", "is_available", "all_reduce", "all_gather",
    "all_gather_object", "broadcast", "broadcast_object_list", "reduce",
    "scatter", "scatter_object_list", "gather", "alltoall",
    "alltoall_single", "reduce_scatter", "send", "recv", "isend", "irecv",
    "barrier", "wait",
    "CollectiveTimeout", "PeerLostError", "COLLECTIVE_FAULTS",
    "coordinated_abort", "abort_on_collective_fault", "coll_timeout_s",
    "PEER_FAILURE_RC", "COLLECTIVE_TIMEOUT_RC",
]


# -- typed collective fault layer --------------------------------------------
#
# Every multi-host object exchange below used to block inside a bare
# ``blocking_key_value_get(key, 60_000)``: a dead peer meant every
# survivor stalled the full minute and then crashed with a backend error
# naming no rank, no op, no tag. The deadline loop here replaces that
# with short polls under one env-configurable TOTAL budget
# (``PADDLE_TPU_COLL_TIMEOUT_S``, default keeps the 60s), capped
# exponential backoff between polls, and — each poll — a check of the
# dead-peer tombstones and coordinated-abort markers the launcher /
# heartbeat layer publishes (heartbeat.py), so a peer that is already
# gone fails the survivors in ~one poll interval with a typed error
# naming exactly who is missing. Single-process / client-less behavior
# is byte-identical: the layer only changes what happens when a peer is
# already gone or never shows up.

DEFAULT_COLL_TIMEOUT_S = 60.0
_BACKOFF_FLOOR_S = 0.002
_BACKOFF_CAP_S = 0.1
# how often the wait loop re-checks tombstone/abort markers: the fast
# path only needs ~poll-interval granularity, and on jaxlib without a
# non-blocking try_get each KV marker probe costs a blocking get —
# checking every single poll would double the pass cost
_MARKER_CHECK_INTERVAL_S = 0.2
# blocking-get budgets for jaxlib without key_value_try_get. The HEAD
# (lowest pending rank) gets an event-driven wait — a blocking get
# returns the instant the key lands, so the common path stays
# server-notified like the old one-key-at-a-time code. Every OTHER
# pending key gets only a presence check (a present key returns
# immediately regardless of budget; an absent one costs the budget), so
# a pass over W pending peers is ~50ms + (W-1)*RTT-bounded-by-10ms, not
# W*50ms — and every key eventually becomes the head as lower ranks
# resolve.
_HEAD_PROBE_MS = 50
_SHORT_PROBE_MS = 10
# sustained every-probe-transport-error window before the wait raises
# UnavailableError (coordinator unreachable) instead of spending the
# whole deadline and then mis-attributing live peers as missing
_TRANSPORT_FAIL_S = 5.0


def _looks_absent(e: BaseException) -> bool:
    """True when a probe error means 'key not present yet' (the normal
    blocked state) rather than a transport failure. jaxlib surfaces
    absence as NOT_FOUND (try_get) or DEADLINE_EXCEEDED (short blocking
    get); dict-backed fakes raise KeyError. Unknown shapes default to
    transport ONLY after a sustained all-probes-failing window, so a
    misclassification cannot fail a healthy wait."""
    if isinstance(e, KeyError):
        return True
    s = str(e)
    return "NOT_FOUND" in s or "DEADLINE_EXCEEDED" in s \
        or "not found" in s.lower()


def _kv_probe(client, key: str, probe_ms: int = _HEAD_PROBE_MS):
    """One non-blocking-ish KV read (shared helper in heartbeat.py:
    ``key_value_try_get`` when the client has it, else a blocking get
    bounded by ``probe_ms``). Raises when the key is (still) absent."""
    from . import heartbeat as _hb
    return _hb._kv_try(client, key, probe_ms=probe_ms)


def coll_timeout_s() -> float:
    """The host-collective deadline budget: PADDLE_TPU_COLL_TIMEOUT_S
    seconds (unset, unparseable, or non-positive values fall back to the
    60s default the bare waits used — a misconfigured knob must degrade
    to today's behavior, not hang forever or spin)."""
    raw = os.environ.get("PADDLE_TPU_COLL_TIMEOUT_S", "")
    if not raw:
        return DEFAULT_COLL_TIMEOUT_S
    try:
        v = float(raw)
    except ValueError:
        return DEFAULT_COLL_TIMEOUT_S
    return v if v > 0 else DEFAULT_COLL_TIMEOUT_S


def _next_delay(delay: float) -> float:
    """Capped exponential backoff schedule for the KV polls."""
    return min(delay * 2.0, _BACKOFF_CAP_S)


class CollectiveTimeout(E.ExecutionTimeoutError):
    """A host collective expired its deadline with contributions still
    missing. Names the op, tag, elapsed time, and the exact ranks whose
    per-rank keys never resolved (derivable attribution: each rank
    writes its own key)."""

    def __init__(self, op: str, tag, elapsed_s: float, missing_ranks,
                 world: int, timeout_s: float):
        self.op = op
        self.tag = tag
        self.elapsed_s = float(elapsed_s)
        self.missing_ranks = sorted(int(r) for r in missing_ranks)
        self.world = int(world)
        self.timeout_s = float(timeout_s)
        super().__init__(
            f"collective '{op}' (tag={tag}) timed out after "
            f"{self.elapsed_s:.1f}s (budget {self.timeout_s:g}s): no "
            f"contribution from rank(s) {self.missing_ranks} of world "
            f"{self.world}",
            hint="raise PADDLE_TPU_COLL_TIMEOUT_S if peers are merely "
                 "slow; a rank that is gone should instead surface as "
                 "PeerLostError via the launcher's death markers")


class PeerLostError(E.UnavailableError):
    """A peer rank is known-dead (launcher death marker / heartbeat
    tombstone) or announced a coordinated abort while this rank was
    blocked in a host collective — the fast path that spares survivors
    the full deadline."""

    def __init__(self, op: str, tag, lost: Dict[int, str],
                 elapsed_s: float, world: int):
        self.op = op
        self.tag = tag
        self.lost_ranks = sorted(int(r) for r in lost)
        self.reasons = {int(r): str(why) for r, why in lost.items()}
        self.elapsed_s = float(elapsed_s)
        self.world = int(world)
        detail = "; ".join(f"rank {r}: {self.reasons[r]}"
                           for r in self.lost_ranks)
        super().__init__(
            f"collective '{op}' (tag={tag}) lost peer rank(s) "
            f"{self.lost_ranks} of world {self.world} after "
            f"{self.elapsed_s:.1f}s ({detail})",
            hint="the elastic manager restarts the world on the "
                 "coordinated-abort rc; see "
                 "docs/fault_tolerance.md#surviving-rank-loss")


COLLECTIVE_FAULTS = (CollectiveTimeout, PeerLostError)


def _reject_multihost_subgroup(op: str, n: int, client):
    """The object-exchange KV paths key by GLOBAL rank, so they serve
    the whole-world group only. A multi-host SUBGROUP call must fail
    TYPED — the old code hung on keys no member writes; silently
    falling back to identity semantics would instead return wrong data
    (each rank seeing only itself)."""
    if client is not None and env.get_world_size() > 1 and 1 < n < \
            env.get_world_size():
        raise E.UnimplementedError(
            f"{op} over a multi-host SUBGROUP ({n} of "
            f"{env.get_world_size()} ranks) is not supported: the "
            "KV exchange keys by global rank",
            hint="use the default (whole-world) group, or exchange "
                 "through tagged whole-world collectives and filter")


def _lost_peers(pending_ranks, me: Optional[int], client) -> Dict[int, str]:
    """{rank: reason} of peers this wait can no longer expect: pending
    ranks with a death marker, plus any OTHER rank that published this
    generation's coordinated-abort marker (its world is going down even
    if it already contributed here)."""
    from . import heartbeat as _hb
    lost = dict(_hb.dead_ranks(sorted(pending_ranks), client=client))
    marker = _hb.read_abort_marker(client=client)
    if marker is not None:
        r = int(marker.get("rank", -1))
        if r >= 0 and r != me and r not in lost:
            lost[r] = ("aborted its collective: "
                       f"{marker.get('reason', 'coordinated abort')}")
    return lost


def _wait_for_keys(client, *, op: str, tag, want: Dict[int, str],
                   world: int, me: Optional[int] = None,
                   timeout_s: Optional[float] = None) -> Dict[int, str]:
    """Deadline-looped multi-key KV wait with failed-rank attribution.
    ``want`` maps the rank a key is ATTRIBUTED to -> the key; returns
    {rank: value} once every key resolved. Raises PeerLostError (fast
    path: tombstone/abort marker observed) or CollectiveTimeout (budget
    spent; names exactly the unresolved ranks)."""
    timeout_s = coll_timeout_s() if timeout_s is None else float(timeout_s)
    t0 = time.monotonic()
    delay = _BACKOFF_FLOOR_S
    pending = dict(want)
    out: Dict[int, str] = {}
    mon = _monitor.enabled()
    next_marker_check = 0.0   # first blocked pass checks immediately
    transport_down_since = None   # first pass where EVERY probe failed
    #                               with a non-absent (transport) error

    def _observe_wait():
        if mon:
            _monitor.observe(
                "dist.collective.wait_ms",
                (time.monotonic() - t0) * 1e3,
                doc="deadline-looped host-collective KV wait wall time "
                    "(success and failure)")

    while pending:
        _faults.hit("collective.kv_get")
        transport_errs = 0
        probes = 0
        for i, r in enumerate(sorted(pending)):
            key = pending[r]
            probes += 1
            try:
                val = _kv_probe(client, key,
                                probe_ms=_HEAD_PROBE_MS if i == 0
                                else _SHORT_PROBE_MS)
            except Exception as e:
                if not _looks_absent(e):
                    transport_errs += 1
                continue
            out[r] = val
            del pending[r]
        if not pending:
            break
        elapsed = time.monotonic() - t0
        # 'key not present yet' and 'coordination service unreachable'
        # are different failures: a pass where EVERY probe died with a
        # transport-shaped error starts (or continues) the outage
        # clock, and a sustained outage raises typed instead of
        # burning the whole deadline and then blaming live peers
        if probes and transport_errs == probes:
            if transport_down_since is None:
                transport_down_since = elapsed
            elif elapsed - transport_down_since >= _TRANSPORT_FAIL_S:
                _observe_wait()
                raise E.UnavailableError(
                    f"coordination service unreachable for "
                    f"{elapsed - transport_down_since:.1f}s while "
                    f"'{op}' (tag={tag}) waited on rank(s) "
                    f"{sorted(pending)} — keys may exist but cannot "
                    "be read (coordinator died?)",
                    hint="this is NOT peer attribution; the elastic "
                         "manager should restart the world")
        else:
            transport_down_since = None
        # tombstone/abort markers are rate-limited: the fast path needs
        # ~poll-interval granularity, and each KV marker probe can cost
        # a 50ms blocking get on jaxlib without a non-blocking read
        lost = None
        if elapsed >= next_marker_check:
            next_marker_check = elapsed + _MARKER_CHECK_INTERVAL_S
            lost = _lost_peers(pending, me, client)
        if lost:
            _observe_wait()
            if mon:
                _monitor.inc("dist.collective.peer_lost",
                             doc="host collectives failed fast on a "
                                 "dead-peer tombstone or abort marker")
            raise PeerLostError(op, tag, lost, elapsed, world)
        if elapsed >= timeout_s:
            _observe_wait()
            if mon:
                _monitor.inc("dist.collective.timeouts",
                             doc="host collectives that expired their "
                                 "deadline with contributions missing")
            raise CollectiveTimeout(op, tag, elapsed, set(pending),
                                    world, timeout_s)
        time.sleep(delay)
        delay = _next_delay(delay)
    _observe_wait()
    return out


def coordinated_abort(exc=None, *, reason: Optional[str] = None,
                      exit_process: bool = True, rc: Optional[int] = None):
    """The failing rank's half of the abort protocol: publish the
    generation-keyed abort marker (peers blocked in ANY wait observe it
    next poll and fail fast as PeerLostError), dump the flight record
    (crash discipline — the black box survives the exit), and leave
    with a typed rc: ``PEER_FAILURE_RC`` for a PeerLostError (peer
    CONFIRMED dead — the elastic manager restarts without blaming this
    rank or engaging scale-in) or ``COLLECTIVE_TIMEOUT_RC`` otherwise
    (the peer may be wedged-but-alive, so the manager's ordinary
    worker-failure heuristics stay engaged). ``exit_process=False``
    publishes + dumps but returns (tests; bespoke supervisors that own
    their exit)."""
    me = env.get_rank()
    why = reason or (f"{type(exc).__name__}: {exc}" if exc is not None
                     else "coordinated abort")
    payload = {"reason": why,
               "op": getattr(exc, "op", None),
               "tag": getattr(exc, "tag", None),
               "lost_ranks": (getattr(exc, "lost_ranks", None)
                              or getattr(exc, "missing_ranks", None))}
    from . import heartbeat as _hb
    _hb.write_abort_marker(me, payload)
    try:
        from ..monitor import trace as _trace
        _trace.instant("collective.abort", rank=me, reason=why[:400])
        _trace.dump_flight_record(reason=f"collective.abort:rank{me}")
    except Exception:
        pass
    print(f"[collective] rank {me} aborting: {why}", file=sys.stderr)
    if exit_process:
        try:
            sys.stderr.flush()
            sys.stdout.flush()
        except Exception:
            pass
        if rc is None:
            rc = PEER_FAILURE_RC if isinstance(exc, PeerLostError) \
                else COLLECTIVE_TIMEOUT_RC
        # os._exit, not sys.exit: atexit could hang on a coordination
        # service whose coordinator is the rank that just died
        os._exit(rc)


class abort_on_collective_fault:
    """Context manager for worker train loops: a CollectiveTimeout /
    PeerLostError escaping the block triggers :func:`coordinated_abort`
    (marker + flight record + rc). With ``exit_process=False`` the
    marker/record still land and the fault re-raises."""

    def __init__(self, exit_process: bool = True):
        self._exit = exit_process

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if et is not None and issubclass(et, COLLECTIVE_FAULTS):
            coordinated_abort(ev, exit_process=self._exit)
        return False


class ReduceOp:
    """Reference: python/paddle/distributed/communication/reduce_op.py."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_REDUCE_FNS = {
    ReduceOp.SUM: jnp.add,
    ReduceOp.MAX: jnp.maximum,
    ReduceOp.MIN: jnp.minimum,
    ReduceOp.PROD: jnp.multiply,
}


class Group:
    """A communicator group (reference: communication/group.py Group).

    Ranks index the global (host-)process world. In the compiled path a
    group corresponds to a mesh axis; ``mesh_axis`` records that binding when
    the group was created from fleet topology (fleet/topology.py)."""

    def __init__(self, rank_in_group: int, gid: int, ranks: List[int],
                 mesh_axis: Optional[str] = None):
        self.rank = rank_in_group
        self.id = gid
        self.ranks = list(ranks)
        self.mesh_axis = mesh_axis

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self) -> bool:
        return env.get_rank() in self.ranks

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_group_map = {}
_next_gid = [1]
_default_group: Optional[Group] = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        world = list(range(env.get_world_size()))
        _default_group = Group(env.get_rank(), 0, world)
        _group_map[0] = _default_group
    return _default_group


def new_group(ranks: Optional[Sequence[int]] = None, backend=None,
              timeout=None, mesh_axis: Optional[str] = None) -> Group:
    """Reference: collective.py:186 new_group. Backend is always the XLA
    collective stack here (``backend`` accepted for parity)."""
    if ranks is None:
        ranks = list(range(env.get_world_size()))
    gid = _next_gid[0]
    _next_gid[0] += 1
    me = env.get_rank()
    rank_in_group = list(ranks).index(me) if me in ranks else -1
    g = Group(rank_in_group, gid, list(ranks), mesh_axis=mesh_axis)
    _group_map[gid] = g
    return g


def get_group(gid: int = 0) -> Optional[Group]:
    if gid == 0:
        return _get_default_group()
    return _group_map.get(gid)


def destroy_process_group(group: Optional[Group] = None):
    global _default_group
    if group is None:
        _group_map.clear()
        _default_group = None
        _next_gid[0] = 1
    else:
        _group_map.pop(group.id, None)


def get_backend(group: Optional[Group] = None) -> str:
    return "xla"


def is_available() -> bool:
    return True


def _group_size(group) -> int:
    return (group or _get_default_group()).nranks


def wait(tensor: Tensor, group=None, use_calc_stream: bool = True):
    """Async-task wait (reference ProcessGroup::Task::Wait). jax.Array
    dispatch is async already; block explicitly."""
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._data)
    return tensor


class _Task:
    """Completed-task handle for isend/irecv/async_op parity (the reference
    returns event-backed tasks; XLA dispatch is async by construction)."""

    def __init__(self, tensor=None):
        self._tensor = tensor

    def wait(self):
        if self._tensor is not None:
            wait(self._tensor)
        return True

    def is_completed(self):
        return True


# -- tensor collectives (eager; see module docstring for semantics) ---------

def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Global-semantics identity for n=1-per-process arrays; AVG divides.

    The hot-path allreduce (DP gradient sync) is NOT this function — it's
    lax.psum inside the compiled train step (comm_ops.all_reduce), or
    implicit from GSPMD when grads carry a dp-sharded batch dim."""
    _note_eager("all_reduce", tensor)
    n = _group_size(group)
    if n > 1 and op == ReduceOp.AVG:
        # Single-controller: array value is already the global sum-of-parts
        # only when each process contributed; with one controller there is
        # exactly one logical value, so SUM/MAX/MIN/PROD are identities.
        pass
    return _Task(tensor) if not sync_op else tensor


def all_gather(tensor_list: List[Tensor], tensor: Tensor, group=None,
               sync_op=True):
    _note_eager("all_gather", tensor)
    n = _group_size(group)
    tensor_list.clear()
    tensor_list.extend(Tensor(tensor._data) for _ in range(n))
    return _Task() if not sync_op else None


# Per-process call counter for coordination-service keys. Collective calls
# execute in the same order on every process (SPMD single-controller-per-host
# discipline), so the counter value is identical across peers at each call —
# unlike id(object_list), which is process-local.
_AG_SEQ = [0]
# Distance-2 key reclamation for the untagged SYMMETRIC exchanges
# (all_gather_object, barrier): a rank entering untagged exchange N has
# completed N-1, which required every peer's N-1 key — so every peer
# finished N-2's reads (it had to, to write its N-1 key) and this
# process's keys from exchanges <= N-2 are provably dead. Without this
# a job that barriers every step grows the coordination-service KV
# store unboundedly (same discipline as the checkpoint stream's
# _begin_tagged_op_and_reclaim). The asymmetric broadcast/scatter
# paths get NO reclamation: their src never blocks, so it has no
# causal proof peers consumed older keys. Tagged calls are the
# caller's to reclaim (the checkpoint layer already does).
_AG_SPENT: list = []     # (seq, key this process wrote)
_BAR_SPENT: list = []


def _reclaim_untagged(client, spent: list, seq: int):
    doomed = [k for s, k in spent if s <= seq - 2]
    spent[:] = [e for e in spent if e[0] > seq - 2]
    for k in doomed:
        try:
            client.key_value_delete(k)
        except Exception:
            pass


def all_gather_object(object_list: List, obj, group=None, tag=None,
                      timeout_s=None):
    """Host object exchange. Multi-host: via the coordination-service KV
    store (jax.distributed client), mirroring TCPStore exchange, under
    the typed fault layer: one TOTAL deadline across all peers (env
    ``PADDLE_TPU_COLL_TIMEOUT_S``), tombstone/abort fast path, and
    failed-rank attribution in the raised error.

    Untagged calls pair across hosts by a per-process sequence counter,
    which is only sound when every host issues its collectives in the
    same order from ONE thread. Callers running off the main thread
    (e.g. the async checkpoint writer) must pass an explicit ``tag``
    that is identical across hosts and unique per exchange — tagged
    rounds use their own KV keys and cannot mis-pair with the counter."""
    _faults.hit("collective.gather")
    _note_eager("all_gather_object")
    n = _group_size(group)
    client = _coord_client()
    _reject_multihost_subgroup("all_gather_object", n, client)
    with _lat("all_gather_object"):
        if client is not None and n > 1 and n == env.get_world_size():
            if tag is None:
                tag = _AG_SEQ[0]
                _AG_SEQ[0] += 1
                _reclaim_untagged(client, _AG_SPENT, tag)
                _AG_SPENT.append((tag, f"ag_{tag}_{env.get_rank()}"))
            me = env.get_rank()
            blob = pickle.dumps(obj).hex()
            client.key_value_set(f"ag_{tag}_{me}", blob)
            got = _wait_for_keys(
                client, op="all_gather_object", tag=tag,
                want={r: f"ag_{tag}_{r}" for r in range(n)},
                world=n, me=me, timeout_s=timeout_s)
            object_list.clear()
            object_list.extend(pickle.loads(bytes.fromhex(got[r]))
                               for r in range(n))
        else:
            object_list.clear()
            object_list.extend(obj for _ in range(n))


def _coord_client():
    try:
        from jax._src import distributed as _dist
        state = _dist.global_state
        return state.client if state.client is not None else None
    except Exception:
        return None


def broadcast(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    _note_eager("broadcast", tensor)
    return _Task(tensor) if not sync_op else tensor


# untagged broadcast/scatter object exchanges pair by their own
# sequence counters (same single-thread program-order contract as
# _AG_SEQ; distinct namespaces so the three families cannot mis-pair)
_BC_SEQ = [0]
_SC_SEQ = [0]


def broadcast_object_list(object_list: List, src: int = 0, group=None,
                          tag=None, timeout_s=None):
    """Reference: communication/broadcast.py broadcast_object_list.
    Multi-host: ``src`` publishes the pickled list once; every other
    rank waits under the typed fault layer (a missing contribution is
    attributed to ``src``). Single-controller worlds keep the identity
    semantics unchanged."""
    _note_eager("broadcast_object_list")
    n = _group_size(group)
    client = _coord_client()
    _reject_multihost_subgroup("broadcast_object_list", n, client)
    with _lat("broadcast_object_list"):
        if client is not None and n > 1 and n == env.get_world_size():
            if tag is None:
                tag = _BC_SEQ[0]
                _BC_SEQ[0] += 1
            me = env.get_rank()
            if me == src:
                client.key_value_set(
                    f"bc_{tag}", pickle.dumps(list(object_list)).hex())
            else:
                got = _wait_for_keys(
                    client, op="broadcast_object_list", tag=tag,
                    want={src: f"bc_{tag}"}, world=n, me=me,
                    timeout_s=timeout_s)
                object_list[:] = pickle.loads(
                    bytes.fromhex(got[src]))
    return object_list


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group=None,
           sync_op=True):
    return _Task(tensor) if not sync_op else tensor


def scatter(tensor: Tensor, tensor_list=None, src: int = 0, group=None,
            sync_op=True):
    if tensor_list:
        me = (group or _get_default_group()).rank
        me = max(me, 0)
        tensor._data = tensor_list[me]._data
    return _Task(tensor) if not sync_op else tensor


def scatter_object_list(out_object_list: List, in_object_list=None,
                        src: int = 0, group=None, tag=None,
                        timeout_s=None):
    """Reference: communication/scatter.py scatter_object_list.
    Multi-host: ``src`` publishes one per-rank key; each rank waits only
    for ITS key under the typed fault layer (a missing contribution is
    attributed to ``src``). Single-controller worlds keep the identity
    semantics unchanged."""
    _note_eager("scatter_object_list")
    client = _coord_client()
    n = _group_size(group)
    _reject_multihost_subgroup("scatter_object_list", n, client)
    with _lat("scatter_object_list"):
        if client is not None and n > 1 and n == env.get_world_size():
            if tag is None:
                tag = _SC_SEQ[0]
                _SC_SEQ[0] += 1
            me = env.get_rank()
            if me == src:
                E.enforce(in_object_list is not None
                          and len(in_object_list) >= n,
                          "scatter_object_list src needs one object per "
                          f"rank (world {n})", E.InvalidArgumentError)
                for r in range(n):
                    if r == me:
                        continue   # src takes its piece locally — an
                        #            unread key would just leak
                    client.key_value_set(
                        f"sc_{tag}_{r}",
                        pickle.dumps(in_object_list[r]).hex())
                out_object_list.clear()
                out_object_list.append(in_object_list[me])
            else:
                got = _wait_for_keys(
                    client, op="scatter_object_list", tag=tag,
                    want={src: f"sc_{tag}_{me}"}, world=n, me=me,
                    timeout_s=timeout_s)
                out_object_list.clear()
                out_object_list.append(pickle.loads(
                    bytes.fromhex(got[src])))
            return
        me = (group or _get_default_group()).rank
        me = max(me, 0)
        out_object_list.clear()
        if in_object_list:
            out_object_list.append(in_object_list[me])


def gather(tensor: Tensor, gather_list=None, dst: int = 0, group=None,
           sync_op=True):
    n = _group_size(group)
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend(Tensor(tensor._data) for _ in range(n))
    return _Task() if not sync_op else None


def alltoall(out_tensor_list: List[Tensor], in_tensor_list: List[Tensor],
             group=None, sync_op=True):
    out_tensor_list.clear()
    out_tensor_list.extend(Tensor(t._data) for t in in_tensor_list)
    return _Task() if not sync_op else None


def alltoall_single(out_tensor: Tensor, in_tensor: Tensor,
                    in_split_sizes=None, out_split_sizes=None, group=None,
                    sync_op=True):
    out_tensor._data = in_tensor._data
    return _Task(out_tensor) if not sync_op else out_tensor


def reduce_scatter(tensor: Tensor, tensor_list: List[Tensor],
                   op=ReduceOp.SUM, group=None, sync_op=True):
    me = (group or _get_default_group()).rank
    me = max(me, 0)
    tensor._data = tensor_list[me]._data
    return _Task(tensor) if not sync_op else tensor


def send(tensor: Tensor, dst: int = 0, group=None, sync_op=True):
    """P2P in the compiled path is lax.ppermute (comm_ops.p2p_permute);
    eager host send between controller processes is not a supported TPU
    pattern — accept for API parity in world-size-1."""
    if _group_size(group) > 1 and env.get_world_size() > 1:
        raise NotImplementedError(
            "eager host-to-host send is not supported; use the compiled "
            "p2p path (paddle_tpu.distributed.comm_ops.p2p_permute) or "
            "object collectives")
    return _Task(tensor) if not sync_op else tensor


def recv(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    if _group_size(group) > 1 and env.get_world_size() > 1:
        raise NotImplementedError(
            "eager host-to-host recv is not supported; use the compiled "
            "p2p path (paddle_tpu.distributed.comm_ops.p2p_permute)")
    return _Task(tensor) if not sync_op else tensor


def isend(tensor: Tensor, dst: int = 0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor: Tensor, src: int = 0, group=None):
    return recv(tensor, src, group, sync_op=False)


# barriers pair by program order like the other untagged exchanges
_BAR_SEQ = [0]


def barrier(group=None, tag=None, timeout_s=None):
    """Host barrier over the coordination service (reference: TCPStore
    barrier / ProcessGroup barrier). Implemented as a per-rank key
    exchange under the typed fault layer (instead of the opaque
    ``wait_at_barrier(..., 60_000)``), so a barrier stranded by a dead
    peer raises PeerLostError/CollectiveTimeout NAMING the absent
    rank(s) — and honors the tombstone fast path."""
    _note_eager("barrier")
    client = _coord_client()
    with _lat("barrier"):
        if client is not None and env.get_world_size() > 1:
            if tag is None:
                tag = _BAR_SEQ[0]
                _BAR_SEQ[0] += 1
                _reclaim_untagged(client, _BAR_SPENT, tag)
                _BAR_SPENT.append((tag, f"bar_{tag}_{env.get_rank()}"))
            n = env.get_world_size()
            me = env.get_rank()
            client.key_value_set(f"bar_{tag}_{me}", "1")
            _wait_for_keys(client, op="barrier", tag=tag,
                           want={r: f"bar_{tag}_{r}" for r in range(n)},
                           world=n, me=me, timeout_s=timeout_s)
        else:
            (jnp.zeros(()) + 0).block_until_ready()



class P2POp:
    """A deferred point-to-point op for batch_isend_irecv (reference:
    distributed/communication/batch_isend_irecv.py P2POp): op is
    paddle.distributed.isend or irecv."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv):
            raise E.InvalidArgumentError(
                "P2POp.op must be paddle.distributed.isend or irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Execute a batch of P2POps, returning their tasks (reference:
    batch_isend_irecv.py). Identity-semantics single-process groups
    complete immediately; multi-process p2p rides the same KV-store
    exchange send/recv use."""
    if not p2p_op_list:
        raise E.InvalidArgumentError("p2p_op_list must not be empty")
    if not all(isinstance(p, P2POp) for p in p2p_op_list):
        raise E.InvalidArgumentError("p2p_op_list must contain only P2POp")
    return [p.op(p.tensor, p.peer, group=p.group) for p in p2p_op_list]
