"""Compiled collective ops — the TPU-native communication backend.

Reference equivalents: the PHI collective kernels + NCCL comm contexts
(paddle/phi/kernels/gpu/all_reduce_kernel.cu area,
phi/core/distributed/nccl_comm_context.cc) and the legacy c_* operators
(paddle/fluid/operators/collective/).

TPU-native design: these are thin, named wrappers over jax.lax collectives,
used *inside* jit/shard_map programs. XLA lowers them onto ICI (intra-slice)
or DCN (inter-slice) — stream management, ring construction, and overlap all
come from the compiler, replacing NCCL's runtime machinery. Use them:

    @partial(shard_map, mesh=mesh, in_specs=..., out_specs=...)
    def step(...):
        g = comm_ops.all_reduce(g, axis="dp")

They also carry Tensor handles transparently (unwrap/wrap) so eager model
code under shard_map keeps the paddle-shaped surface.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from .. import monitor as _monitor
from ..core.tensor import Tensor, _nbytes_of
from ..core import enforce as E

__all__ = [
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "p2p_permute", "broadcast", "axis_index", "axis_size", "psum", "pmean",
    "pmax", "pmin",
]

AxisName = Union[str, Sequence[str]]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _rewrap(x, raw):
    return Tensor(raw, stop_gradient=x.stop_gradient) \
        if isinstance(x, Tensor) else raw


def _note(op: str, raw):
    """Monitor-gated collective accounting. These wrappers run at TRACE
    time (inside jit/shard_map), so counts are per-compile, not
    per-execution — the honest observable without a host callback in
    the compiled program. ``bytes`` is the per-device operand size.

    Suppression: the observability layer's OWN re-traces (MFU capture,
    lazy memory/comm analyzers — monitor.suppress_accounting) are
    muted, so a program's collectives count exactly once per real
    compile no matter how often a scrape re-lowers it."""
    if not _monitor.enabled() or _monitor.suppressed():
        return
    _monitor.inc(f"dist.{op}.calls",
                 doc="traced compiled-collective call sites")
    nbytes = _nbytes_of(raw)
    if nbytes:
        _monitor.inc(f"dist.{op}.bytes", nbytes,
                     doc="per-device operand bytes at trace time")


# These wrappers are deliberately NOT wall-timed: a named-axis
# collective can only execute inside a trace (eager calls raise on the
# unbound axis name), and a trace-time measurement would record
# microseconds of tracing as "collective latency". Runtime
# ``comm.latency.*`` histograms live at the host seam
# (``distributed/collective.py``); the in-graph collectives are
# accounted by ``_note`` and the compiled-HLO scan
# (``monitor/comms.py``).


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName) -> int:
    return lax.axis_size(axis)


def all_reduce(x, axis: AxisName, op: str = "sum"):
    """c_allreduce_{sum,max,min,prod,avg} equivalent → lax.psum/pmax/pmin."""
    raw = _unwrap(x)
    _note("all_reduce", raw)
    if op == "sum":
        out = lax.psum(raw, axis)
    elif op == "max":
        out = lax.pmax(raw, axis)
    elif op == "min":
        out = lax.pmin(raw, axis)
    elif op in ("avg", "mean"):
        out = lax.pmean(raw, axis)
    elif op == "prod":
        # Sign-safe product: |x| via exp(psum(log)), sign via parity of
        # negative counts, zeros via a mask (log(0) would poison psum).
        zero = raw == 0
        absx = jnp.where(zero, 1.0, jnp.abs(raw))
        mag = jnp.exp(lax.psum(jnp.log(absx), axis))
        neg = lax.psum((raw < 0).astype(raw.dtype), axis)
        sign = 1.0 - 2.0 * (neg % 2)
        any_zero = lax.pmax(zero.astype(raw.dtype), axis)
        out = jnp.where(any_zero > 0, 0.0, sign * mag).astype(raw.dtype)
    else:
        raise E.InvalidArgumentError(f"unknown reduce op {op}")
    return _rewrap(x, out)


def psum(x, axis: AxisName):
    return _rewrap(x, lax.psum(_unwrap(x), axis))


def pmean(x, axis: AxisName):
    return _rewrap(x, lax.pmean(_unwrap(x), axis))


def pmax(x, axis: AxisName):
    return _rewrap(x, lax.pmax(_unwrap(x), axis))


def pmin(x, axis: AxisName):
    return _rewrap(x, lax.pmin(_unwrap(x), axis))


def all_gather(x, axis: AxisName, *, gather_dim: int = 0, tiled: bool = True):
    """c_allgather equivalent. ``tiled=True`` concatenates along
    ``gather_dim`` (the common Megatron-SP use); False stacks a new dim."""
    raw = _unwrap(x)
    _note("all_gather", raw)
    out = lax.all_gather(raw, axis, axis=gather_dim, tiled=tiled)
    return _rewrap(x, out)


def reduce_scatter(x, axis: AxisName, *, scatter_dim: int = 0):
    """c_reducescatter equivalent → lax.psum_scatter (ICI-ring lowered)."""
    raw = _unwrap(x)
    _note("reduce_scatter", raw)
    out = lax.psum_scatter(raw, axis, scatter_dimension=scatter_dim,
                           tiled=True)
    return _rewrap(x, out)


def all_to_all(x, axis: AxisName, *, split_dim: int, concat_dim: int):
    """alltoall equivalent (MoE dispatch / s→s reshard) → lax.all_to_all."""
    raw = _unwrap(x)
    _note("all_to_all", raw)
    out = lax.all_to_all(raw, axis, split_axis=split_dim,
                         concat_axis=concat_dim, tiled=True)
    return _rewrap(x, out)


def p2p_permute(x, axis: AxisName, perm: Sequence[tuple]):
    """Point-to-point over a ring — the PP send/recv primitive.

    Reference: ProcessGroupNCCL::Send/Recv (process_group_nccl.cc:598,637) +
    pp_utils/p2p_communication.py. TPU-native: lax.ppermute compiles to ICI
    collective-permute; ``perm`` is [(src, dst), ...] in axis coordinates.
    """
    raw = _unwrap(x)
    _note("p2p_permute", raw)
    out = lax.ppermute(raw, axis, perm=perm)
    return _rewrap(x, out)


def broadcast(x, axis: AxisName, src: int = 0):
    """c_broadcast equivalent: keep src's value on all ranks of the axis."""
    raw = _unwrap(x)
    _note("broadcast", raw)
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == src, raw, jnp.zeros_like(raw))
    return _rewrap(x, lax.psum(masked, axis))
