"""Worker liveness heartbeats for elastic training.

Reference capability: distributed/fleet/elastic/manager.py — etcd-lease
heartbeats give the elastic manager a membership signal, so a wedged or
silently-dead worker is detected, not just a crashed one. TPU-native
redesign: one controller per host (launch/main.py) watches per-rank
heartbeat FILES (mtime = last beat) — no external etcd; the transport is
the shared filesystem the launcher already owns for worker logs. (A
multi-host deployment can point PADDLE_HEARTBEAT_DIR at shared storage;
the beats are tiny O(ranks) touches.)

Two beat sources, two failure classes:
- AUTO beats: a daemon thread touches the file every interval — detects
  dead/killed/deadlocked-at-exec processes (the thread dies with them).
- PROGRESS beats: the training loop calls ``beat(step=n)`` — detects
  WEDGED-BUT-ALIVE workers (hung collective, stuck IO), which auto
  beats cannot see. The watcher uses the progress threshold only for
  workers that have opted in by emitting at least one progress beat.

Multi-host transport (no shared filesystem needed): beats ALSO publish
to the jax.distributed coordination-service KV store when a client is
live (the same store TCPStore maps to). ``KVHeartbeatWatcher`` measures
staleness clock-skew-free — it tracks when each rank's beat VALUE last
CHANGED on the watcher's own clock, never comparing cross-host
timestamps — and ``start_kv_relay`` (rank-0 worker) mirrors every
rank's KV beats into the local controller's heartbeat dir, so the
file-based launch watcher covers remote hosts unchanged.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

_AUTO_SUFFIX = ".alive"
_PROGRESS_SUFFIX = ".progress"
_DEAD_SUFFIX = ".dead"
_KV_PREFIX = "paddle_hb"
_DEAD_KV_PREFIX = "pt_dead"
_ABORT_KV_PREFIX = "pt_abort"
_state = {"thread": None, "stop": None, "dir": None, "rank": None,
          "seq": 0}


def _touch(path, payload=None):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload or {"t": time.time()}))
    os.replace(tmp, path)


def _kv_client():
    """The live coordination-service client, or None (single-process /
    pre-init)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:
        return None


def _kv_publish(kind: str, rank: int, payload: dict):
    client = _kv_client()
    if client is None:
        return False
    _state["seq"] += 1
    payload = dict(payload, seq=_state["seq"])
    try:
        client.key_value_set(f"{_KV_PREFIX}/{kind}/rank{rank}",
                             json.dumps(payload), allow_overwrite=True)
        return True
    except Exception:
        return False


def start(dir_path: Optional[str] = None, rank: Optional[int] = None,
          interval: float = 1.0):
    """Start the auto-beat daemon thread (idempotent). Called by
    init_parallel_env when PADDLE_HEARTBEAT_DIR is set."""
    dir_path = dir_path or os.environ.get("PADDLE_HEARTBEAT_DIR")
    if not dir_path:
        return False
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    if _state["thread"] is not None and _state["thread"].is_alive():
        return True
    os.makedirs(dir_path, exist_ok=True)
    stop = threading.Event()
    path = os.path.join(dir_path, f"rank{rank}{_AUTO_SUFFIX}")

    def loop():
        while not stop.is_set():
            try:
                _touch(path)
            except OSError:
                pass
            _kv_publish("auto", rank, {"t": time.time()})
            stop.wait(interval)

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    _state.update(thread=th, stop=stop, dir=dir_path, rank=rank)
    return True


def stop():
    if _state["stop"] is not None:
        _state["stop"].set()
        _state["thread"] = None


def beat(step: Optional[int] = None):
    """Emit a PROGRESS beat from the training loop. A worker that emits
    one opts into wedge detection: the watcher kills the job if its
    progress beat goes stale. Publishes to the file dir (when set) AND
    the KV store (when a coordination client is live)."""
    rank = _state["rank"] if _state["rank"] is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    dir_path = _state["dir"] or os.environ.get("PADDLE_HEARTBEAT_DIR")
    if dir_path:
        os.makedirs(dir_path, exist_ok=True)
        _touch(os.path.join(dir_path, f"rank{rank}{_PROGRESS_SUFFIX}"),
               {"t": time.time(), "step": step})
    _kv_publish("progress", rank, {"t": time.time(), "step": step})


def check_stale(dir_path: str, ranks, auto_timeout: float,
                progress_timeout: float,
                started_at: Optional[float] = None) -> Dict[int, str]:
    """Watcher side: {rank: reason} for every stale worker among
    ``ranks`` (GLOBAL rank ids — a node's watcher passes its own ranks,
    node_rank*nproc..+nproc). A rank with no auto beat yet is stale only
    once ``started_at`` is more than auto_timeout old (a worker can
    wedge before its first beat — import hang, stuck backend init);
    progress staleness applies only to ranks that have beaten progress
    at least once."""
    now = time.time()
    stale = {}
    for rank in ranks:
        auto = os.path.join(dir_path, f"rank{rank}{_AUTO_SUFFIX}")
        prog = os.path.join(dir_path, f"rank{rank}{_PROGRESS_SUFFIX}")
        try:
            age = now - os.stat(auto).st_mtime
            if auto_timeout > 0 and age > auto_timeout:
                stale[rank] = f"no liveness beat for {age:.1f}s"
                continue
        except OSError:
            # never beat at all: stale once the startup grace (one
            # auto_timeout from job start) is spent
            if (auto_timeout > 0 and started_at is not None
                    and now - started_at > auto_timeout):
                stale[rank] = ("never emitted a liveness beat "
                               f"({now - started_at:.1f}s since launch)")
                continue
        try:
            page = now - os.stat(prog).st_mtime
            if progress_timeout > 0 and page > progress_timeout:
                stale[rank] = f"no training progress for {page:.1f}s"
        except OSError:
            pass   # never opted in
    return stale


# -- named beats (serving replicas & other non-rank participants) ------------
#
# The rank-keyed files above serve elastic TRAINING; the elastic SERVING
# controller (fleet/elastic.py run_serving) watches arbitrarily-NAMED
# participants — "replica3" is not a trainer rank. Same transport, same
# staleness semantics, name-keyed files.

def touch_named(dir_path: str, name: str, payload: Optional[dict] = None):
    """One liveness beat for a named participant (``<name>.alive``)."""
    os.makedirs(dir_path, exist_ok=True)
    _touch(os.path.join(dir_path, f"{name}{_AUTO_SUFFIX}"),
           payload or {"t": time.time()})


def start_named(dir_path: str, name: str,
                interval: float = 1.0) -> threading.Event:
    """Auto-beat daemon for a named participant; returns the stop
    event. The thread dies with the process — a kill -9'd replica goes
    stale within ``interval`` + the watcher's timeout."""
    os.makedirs(dir_path, exist_ok=True)
    stop = threading.Event()
    path = os.path.join(dir_path, f"{name}{_AUTO_SUFFIX}")

    def loop():
        while not stop.is_set():
            try:
                _touch(path)
            except OSError:
                pass
            stop.wait(interval)

    threading.Thread(target=loop, daemon=True).start()
    return stop


_NAMED_KV_PREFIX = "pt_named"


def publish_named(name: str, payload: dict, *,
                  dir_path: Optional[str] = None, client=None) -> bool:
    """Publish a named participant's payload on BOTH transports: the
    beat file (``touch_named`` — the payload IS the beat, so a replica
    publishing telemetry frames needs no separate auto-beat daemon to
    stay live under ``stale_names``) and the coordination-service KV
    store (key ``pt_named/<name>``) for controllers with no shared
    filesystem. Never raises; returns True when at least one transport
    took the write."""
    ok = False
    d = _marker_dir(dir_path)
    if d:
        try:
            touch_named(d, name, payload)
            ok = True
        except (OSError, TypeError, ValueError):
            # TypeError/ValueError: a payload json.dumps can't take
            # (e.g. numpy scalars from a user slo_fn) must report
            # "transport took nothing", not crash the serving loop
            # the docstring promises never to take down
            pass
    client = client if client is not None else _kv_client()
    if client is not None:
        try:
            client.key_value_set(f"{_NAMED_KV_PREFIX}/{name}",
                                 json.dumps(payload),
                                 allow_overwrite=True)
            ok = True
        except Exception:
            pass
    return ok


def read_named(name: str, *, dir_path: Optional[str] = None,
               client=None, env_fallback: bool = True) -> Optional[dict]:
    """The freshest published payload for a named participant across
    both transports (a ``seq`` field, when both carry one, breaks the
    tie — the file and KV copies of one publisher never regress
    against each other). None when neither transport has it.
    ``env_fallback=False`` confines the file leg to the EXPLICIT
    ``dir_path`` (skipped when None) instead of the
    ``PADDLE_HEARTBEAT_DIR`` fallback — a KV-only reader must not
    ingest an unrelated fleet's generic ``replicaN`` payloads off a
    launcher-set env dir."""
    best = None
    d = _marker_dir(dir_path) if env_fallback else dir_path
    if d:
        try:
            with open(os.path.join(d, f"{name}{_AUTO_SUFFIX}")) as f:
                best = json.load(f)
        except (OSError, ValueError):
            best = None
    client = client if client is not None else _kv_client()
    if client is not None:
        try:
            kv_payload = json.loads(_kv_try(
                client, f"{_NAMED_KV_PREFIX}/{name}", probe_ms=10))
        except Exception:
            kv_payload = None
        if isinstance(kv_payload, dict):
            if not isinstance(best, dict) or \
                    _seq_of(kv_payload) > _seq_of(best):
                best = kv_payload
    return best if isinstance(best, dict) else None


def _seq_of(payload: dict) -> float:
    """A payload's seq as a comparable number; -1 when missing or
    malformed. Payloads are remote input — a corrupt KV copy carrying
    ``"seq": "5"`` must lose the tiebreak, not raise a TypeError that
    discards the valid file-transport copy too (and gets a healthy
    frame-is-the-beat replica stale-killed)."""
    s = payload.get("seq")
    if isinstance(s, bool) or not isinstance(s, (int, float)) \
            or s != s:
        return -1
    return s


def remove_named(dir_path: Optional[str], name: str, *, client=None,
                 env_fallback: bool = True):
    """GC a stopped or replaced named participant: drop its beat file
    and its KV payload key. Without this a long-lived controller dir
    accumulates one ``<name>.alive`` per replica the fleet ever ran —
    ``run_serving`` sweeps on every stop/replace. Idempotent, never
    raises. ``env_fallback=False`` confines the file removal to the
    EXPLICIT ``dir_path`` (skipped when None): a KV-only sweeper in a
    process where the launcher exported ``PADDLE_HEARTBEAT_DIR`` must
    not delete an unrelated fleet's beat files."""
    d = _marker_dir(dir_path) if env_fallback else dir_path
    if d:
        try:
            os.remove(os.path.join(d, f"{name}{_AUTO_SUFFIX}"))
        except OSError:
            pass
    client = client if client is not None else _kv_client()
    if client is not None:
        try:
            client.key_value_delete(f"{_NAMED_KV_PREFIX}/{name}")
        except Exception:
            pass


def stale_names(dir_path: str, names, timeout: float,
                started_at=None) -> Dict[str, str]:
    """{name: reason} for every stale named participant. Same contract
    as :func:`check_stale`'s auto-beat leg: a participant that never
    beat is stale only once its startup grace (one ``timeout`` from
    ``started_at``) is spent. ``started_at`` may be a single float or
    a {name: float} map (per-replica spawn times). A beat file OLDER
    than ``started_at`` is a leftover from a previous incarnation of
    the name (controllers reuse replica0, replica1, ...) and counts as
    never-beat — a fresh healthy replica must get its startup grace,
    not be declared stale off a predecessor's mtime."""
    now = time.time()
    stale: Dict[str, str] = {}
    for name in names:
        path = os.path.join(dir_path, f"{name}{_AUTO_SUFFIX}")
        t0 = started_at.get(name) if isinstance(started_at, dict) \
            else started_at
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            mtime = None
        if mtime is not None and (t0 is None or mtime >= t0):
            age = now - mtime
            if timeout > 0 and age > timeout:
                stale[name] = f"no liveness beat for {age:.1f}s"
        elif timeout > 0 and t0 is not None and now - t0 > timeout:
            stale[name] = ("never emitted a liveness beat "
                           f"({now - t0:.1f}s since spawn)")
    return stale


# -- KV-store transport (multi-host, no shared filesystem) -------------------

class KVHeartbeatWatcher:
    """Staleness over KV beats, clock-skew-free: a rank's age is the
    time since its beat VALUE last changed, measured on THIS process's
    clock (cross-host timestamps are never compared — the etcd-lease
    property the reference relies on)."""

    def __init__(self, client=None):
        self._client = client if client is not None else _kv_client()
        # key -> (last value, local time the value last changed)
        self._last: Dict[str, tuple] = {}

    def _age(self, key: str, now: float) -> Optional[float]:
        try:
            val = self._client.key_value_try_get(key)
        except Exception:
            return None                 # never published
        prev = self._last.get(key)
        if prev is None or prev[0] != val:
            self._last[key] = (val, now)
            return 0.0
        return now - prev[1]

    def check(self, ranks, auto_timeout: float, progress_timeout: float,
              started_at: Optional[float] = None) -> Dict[int, str]:
        """Same contract as ``check_stale``, over the KV transport."""
        now = time.time()
        stale: Dict[int, str] = {}
        for rank in ranks:
            age = self._age(f"{_KV_PREFIX}/auto/rank{rank}", now)
            if age is None:
                if (auto_timeout > 0 and started_at is not None
                        and now - started_at > auto_timeout):
                    stale[rank] = ("never published a liveness beat "
                                   f"({now - started_at:.1f}s since "
                                   "launch)")
                continue
            if auto_timeout > 0 and age > auto_timeout:
                stale[rank] = f"no liveness beat for {age:.1f}s"
                continue
            page = self._age(f"{_KV_PREFIX}/progress/rank{rank}", now)
            if page is not None and progress_timeout > 0 \
                    and page > progress_timeout:
                stale[rank] = f"no training progress for {page:.1f}s"
        return stale

    def latest(self, kind: str, rank: int) -> Optional[dict]:
        try:
            return json.loads(self._client.key_value_try_get(
                f"{_KV_PREFIX}/{kind}/rank{rank}"))
        except Exception:
            return None


def start_kv_relay(dir_path: str, world_ranks, interval: float = 1.0,
                   client=None) -> Optional[threading.Event]:
    """Rank-0 side: mirror every rank's KV beats into ``dir_path`` as
    the files the launch controller already watches, so a controller
    with no shared filesystem (and no coordination client of its own)
    sees remote hosts' liveness through its local disk. A rank's file
    is touched only when its KV beat VALUE changes, preserving the
    staleness signal. Returns the stop event (None if no client)."""
    watcher = KVHeartbeatWatcher(client)
    if watcher._client is None:
        return None
    os.makedirs(dir_path, exist_ok=True)
    stop = threading.Event()
    seen: Dict[str, str] = {}

    def loop():
        while not stop.is_set():
            for rank in world_ranks:
                for kind, suffix in (("auto", _AUTO_SUFFIX),
                                     ("progress", _PROGRESS_SUFFIX)):
                    key = f"{_KV_PREFIX}/{kind}/rank{rank}"
                    try:
                        val = watcher._client.key_value_try_get(key)
                    except Exception:
                        continue
                    if seen.get(key) == val:
                        continue
                    seen[key] = val
                    try:
                        _touch(os.path.join(
                            dir_path, f"rank{rank}{suffix}"),
                            json.loads(val))
                    except (OSError, ValueError):
                        pass
            stop.wait(interval)

    threading.Thread(target=loop, daemon=True).start()
    return stop


# -- dead-peer tombstones + coordinated-abort markers ------------------------
#
# The fast path of the typed collective fault layer (collective.py): a
# rank blocked in a KV wait polls these each backoff step, so a peer the
# launcher already reaped — or one that aborted on its own typed fault —
# fails the survivors in ~one poll interval instead of the full
# PADDLE_TPU_COLL_TIMEOUT_S deadline. Two transports, same as the beats:
# per-rank FILES in the heartbeat dir (written by the launch controller,
# which has no coordination client) and KV keys (written by workers,
# visible without a shared filesystem). Markers are GENERATION-keyed by
# the elastic run index (PR 2's reclamation discipline): a marker from
# world incarnation g-1 must never kill incarnation g after a restart,
# and writers best-effort delete their stale-generation KV keys.

def elastic_generation() -> int:
    """The elastic world incarnation markers are keyed by (0 = first;
    AdaptiveElasticManager exports PADDLE_ELASTIC_RUN per relaunch)."""
    try:
        return int(os.environ.get("PADDLE_ELASTIC_RUN", "0"))
    except ValueError:
        return 0


def _marker_dir(dir_path: Optional[str]) -> Optional[str]:
    return dir_path or os.environ.get("PADDLE_HEARTBEAT_DIR")


def _kv_try(client, key: str, probe_ms: int = 50):
    """Short KV probe (also collective.py's wait-loop poll):
    ``key_value_try_get`` when the client has one (fakes, newer
    jaxlib), else a ``probe_ms`` blocking get — jaxlib <= 0.4.x has no
    non-blocking read. Raises when absent."""
    try_get = getattr(client, "key_value_try_get", None)
    if try_get is not None:
        return try_get(key)
    return client.blocking_key_value_get(key, probe_ms)


def _job_identity(job: Optional[str]) -> Optional[str]:
    """Markers are scoped to one JOB: its rendezvous address (every
    launch picks a fresh free port by default, so two successive jobs
    reusing a log_dir — same generation 0 — can never honor each
    other's markers, while multi-node controllers of ONE job share the
    master and therefore the markers)."""
    return job or os.environ.get("PADDLE_MASTER")


def _job_matches(payload: dict) -> bool:
    """A marker counts only for the job that wrote it; markers or
    readers without a job identity (direct API use, tests) match
    everything."""
    mine = os.environ.get("PADDLE_MASTER")
    theirs = payload.get("job")
    return theirs is None or mine is None or theirs == mine


def mark_dead(rank: int, reason: str, *, dir_path: Optional[str] = None,
              client=None, generation: Optional[int] = None,
              job: Optional[str] = None):
    """Write rank ``rank``'s death marker (file + KV, whichever
    transports are reachable). Idempotent; never raises."""
    gen = elastic_generation() if generation is None else int(generation)
    payload = {"rank": int(rank), "reason": str(reason), "gen": gen,
               "job": _job_identity(job), "t": time.time()}
    d = _marker_dir(dir_path)
    if d:
        try:
            os.makedirs(d, exist_ok=True)
            _touch(os.path.join(d, f"rank{rank}.g{gen}{_DEAD_SUFFIX}"),
                   payload)
        except OSError:
            pass
    client = client if client is not None else _kv_client()
    if client is not None:
        try:
            client.key_value_set(
                f"{_DEAD_KV_PREFIX}/g{gen}/rank{rank}",
                json.dumps(payload), allow_overwrite=True)
        except Exception:
            pass


def dead_ranks(ranks, *, dir_path: Optional[str] = None, client=None,
               generation: Optional[int] = None) -> Dict[int, str]:
    """{rank: reason} for every rank in ``ranks`` with a death marker of
    THIS generation on either transport."""
    gen = elastic_generation() if generation is None else int(generation)
    d = _marker_dir(dir_path)
    client = client if client is not None else _kv_client()
    out: Dict[int, str] = {}
    for rank in ranks:
        payload = None
        if d:
            try:
                with open(os.path.join(
                        d, f"rank{rank}.g{gen}{_DEAD_SUFFIX}")) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                payload = None
        if payload is None and client is not None:
            try:
                # presence check only — never WAIT for a marker to
                # appear (the caller polls); 10ms bounds per-rank cost
                # on clients whose only read is a blocking get
                payload = json.loads(_kv_try(
                    client, f"{_DEAD_KV_PREFIX}/g{gen}/rank{rank}",
                    probe_ms=10))
            except Exception:
                payload = None
        if payload is not None and _job_matches(payload):
            out[int(rank)] = str(payload.get("reason", "dead"))
    return out


def write_abort_marker(rank: int, payload: dict, *,
                       dir_path: Optional[str] = None, client=None,
                       generation: Optional[int] = None,
                       job: Optional[str] = None):
    """Publish the coordinated-abort marker: the failing rank announces
    its typed collective fault so every surviving peer's wait loop fails
    fast instead of waiting out its own deadline. One marker per
    generation (last writer wins — any marker means the world is going
    down). Best-effort reclamation: the previous generation's KV marker
    is deleted. Never raises."""
    gen = elastic_generation() if generation is None else int(generation)
    payload = dict(payload, rank=int(rank), gen=gen,
                   job=_job_identity(job), t=time.time())
    d = _marker_dir(dir_path)
    if d:
        try:
            os.makedirs(d, exist_ok=True)
            _touch(os.path.join(d, f"abort.g{gen}.json"), payload)
        except OSError:
            pass
    client = client if client is not None else _kv_client()
    if client is not None:
        try:
            client.key_value_set(f"{_ABORT_KV_PREFIX}/g{gen}",
                                 json.dumps(payload),
                                 allow_overwrite=True)
        except Exception:
            pass
        if gen > 0:
            try:
                client.key_value_delete(
                    f"{_ABORT_KV_PREFIX}/g{gen - 1}")
            except Exception:
                pass


def read_abort_marker(*, dir_path: Optional[str] = None, client=None,
                      generation: Optional[int] = None) -> Optional[dict]:
    """This generation's abort marker payload, or None."""
    gen = elastic_generation() if generation is None else int(generation)
    d = _marker_dir(dir_path)
    if d:
        try:
            with open(os.path.join(d, f"abort.g{gen}.json")) as f:
                payload = json.load(f)
            if _job_matches(payload):
                return payload
        except (OSError, ValueError):
            pass
    client = client if client is not None else _kv_client()
    if client is not None:
        try:
            payload = json.loads(_kv_try(client,
                                         f"{_ABORT_KV_PREFIX}/g{gen}",
                                         probe_ms=10))
            if _job_matches(payload):
                return payload
        except Exception:
            pass
    return None


_MARKER_GEN_RE = None


def clear_run_markers(dir_path: str, generation: Optional[int] = None,
                      own_ranks=()):
    """Launcher start-of-run hygiene over a shared heartbeat dir. Drops
    marker FILES that are provably stale from THIS controller's view:

    - every marker of a generation OLDER than ``generation`` (elastic
      manager paths export a fresh PADDLE_ELASTIC_RUN per relaunch);
    - current-generation markers for ``own_ranks`` — this node's
      workers haven't spawned yet, so any marker for them predates
      this job (a re-run with a pinned --master reusing a log_dir);
    - current-generation ABORT markers — one present at launcher start
      cannot have been written by this not-yet-started incarnation
      (worst case it was a cross-node peer's live abort: the peer's
      own controller still fails that job; only the fast path is lost).

    Other nodes' current-generation rank tombstones are PRESERVED — a
    later-starting controller of a multi-node job must not delete a
    peer node's live markers. Residual limitation (documented in
    docs/fault_tolerance.md): a multi-node run with a pinned master
    reusing a log_dir should clean ``heartbeats/`` between jobs.
    Markers with no parseable generation are legacy debris and are
    dropped. KV markers need no sweep — every launch rendezvouses a
    fresh coordination service."""
    import re
    global _MARKER_GEN_RE
    if _MARKER_GEN_RE is None:
        _MARKER_GEN_RE = re.compile(
            r"(?:^abort\.g(\d+)\.json$|\.g(\d+)" +
            re.escape(_DEAD_SUFFIX) + r"$)")
    gen = elastic_generation() if generation is None else int(generation)
    own = {int(r) for r in own_ranks}
    try:
        names = os.listdir(dir_path)
    except OSError:
        return
    for name in names:
        if not (name.endswith(_DEAD_SUFFIX) or name.startswith("abort.g")):
            continue
        m = _MARKER_GEN_RE.search(name)
        marker_gen = int(m.group(1) or m.group(2)) if m else None
        if marker_gen is not None and marker_gen >= gen:
            if name.startswith("abort.g"):
                pass                 # pre-start abort: provably stale
            else:
                rm = re.match(r"^rank(\d+)\.", name)
                if rm is None or int(rm.group(1)) not in own:
                    continue         # a peer node may own it — keep
        try:
            os.remove(os.path.join(dir_path, name))
        except OSError:
            pass
