"""Worker liveness heartbeats for elastic training.

Reference capability: distributed/fleet/elastic/manager.py — etcd-lease
heartbeats give the elastic manager a membership signal, so a wedged or
silently-dead worker is detected, not just a crashed one. TPU-native
redesign: one controller per host (launch/main.py) watches per-rank
heartbeat FILES (mtime = last beat) — no external etcd; the transport is
the shared filesystem the launcher already owns for worker logs. (A
multi-host deployment can point PADDLE_HEARTBEAT_DIR at shared storage;
the beats are tiny O(ranks) touches.)

Two beat sources, two failure classes:
- AUTO beats: a daemon thread touches the file every interval — detects
  dead/killed/deadlocked-at-exec processes (the thread dies with them).
- PROGRESS beats: the training loop calls ``beat(step=n)`` — detects
  WEDGED-BUT-ALIVE workers (hung collective, stuck IO), which auto
  beats cannot see. The watcher uses the progress threshold only for
  workers that have opted in by emitting at least one progress beat.

Multi-host transport (no shared filesystem needed): beats ALSO publish
to the jax.distributed coordination-service KV store when a client is
live (the same store TCPStore maps to). ``KVHeartbeatWatcher`` measures
staleness clock-skew-free — it tracks when each rank's beat VALUE last
CHANGED on the watcher's own clock, never comparing cross-host
timestamps — and ``start_kv_relay`` (rank-0 worker) mirrors every
rank's KV beats into the local controller's heartbeat dir, so the
file-based launch watcher covers remote hosts unchanged.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

_AUTO_SUFFIX = ".alive"
_PROGRESS_SUFFIX = ".progress"
_KV_PREFIX = "paddle_hb"
_state = {"thread": None, "stop": None, "dir": None, "rank": None,
          "seq": 0}


def _touch(path, payload=None):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload or {"t": time.time()}))
    os.replace(tmp, path)


def _kv_client():
    """The live coordination-service client, or None (single-process /
    pre-init)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:
        return None


def _kv_publish(kind: str, rank: int, payload: dict):
    client = _kv_client()
    if client is None:
        return False
    _state["seq"] += 1
    payload = dict(payload, seq=_state["seq"])
    try:
        client.key_value_set(f"{_KV_PREFIX}/{kind}/rank{rank}",
                             json.dumps(payload), allow_overwrite=True)
        return True
    except Exception:
        return False


def start(dir_path: Optional[str] = None, rank: Optional[int] = None,
          interval: float = 1.0):
    """Start the auto-beat daemon thread (idempotent). Called by
    init_parallel_env when PADDLE_HEARTBEAT_DIR is set."""
    dir_path = dir_path or os.environ.get("PADDLE_HEARTBEAT_DIR")
    if not dir_path:
        return False
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    if _state["thread"] is not None and _state["thread"].is_alive():
        return True
    os.makedirs(dir_path, exist_ok=True)
    stop = threading.Event()
    path = os.path.join(dir_path, f"rank{rank}{_AUTO_SUFFIX}")

    def loop():
        while not stop.is_set():
            try:
                _touch(path)
            except OSError:
                pass
            _kv_publish("auto", rank, {"t": time.time()})
            stop.wait(interval)

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    _state.update(thread=th, stop=stop, dir=dir_path, rank=rank)
    return True


def stop():
    if _state["stop"] is not None:
        _state["stop"].set()
        _state["thread"] = None


def beat(step: Optional[int] = None):
    """Emit a PROGRESS beat from the training loop. A worker that emits
    one opts into wedge detection: the watcher kills the job if its
    progress beat goes stale. Publishes to the file dir (when set) AND
    the KV store (when a coordination client is live)."""
    rank = _state["rank"] if _state["rank"] is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    dir_path = _state["dir"] or os.environ.get("PADDLE_HEARTBEAT_DIR")
    if dir_path:
        os.makedirs(dir_path, exist_ok=True)
        _touch(os.path.join(dir_path, f"rank{rank}{_PROGRESS_SUFFIX}"),
               {"t": time.time(), "step": step})
    _kv_publish("progress", rank, {"t": time.time(), "step": step})


def check_stale(dir_path: str, ranks, auto_timeout: float,
                progress_timeout: float,
                started_at: Optional[float] = None) -> Dict[int, str]:
    """Watcher side: {rank: reason} for every stale worker among
    ``ranks`` (GLOBAL rank ids — a node's watcher passes its own ranks,
    node_rank*nproc..+nproc). A rank with no auto beat yet is stale only
    once ``started_at`` is more than auto_timeout old (a worker can
    wedge before its first beat — import hang, stuck backend init);
    progress staleness applies only to ranks that have beaten progress
    at least once."""
    now = time.time()
    stale = {}
    for rank in ranks:
        auto = os.path.join(dir_path, f"rank{rank}{_AUTO_SUFFIX}")
        prog = os.path.join(dir_path, f"rank{rank}{_PROGRESS_SUFFIX}")
        try:
            age = now - os.stat(auto).st_mtime
            if auto_timeout > 0 and age > auto_timeout:
                stale[rank] = f"no liveness beat for {age:.1f}s"
                continue
        except OSError:
            # never beat at all: stale once the startup grace (one
            # auto_timeout from job start) is spent
            if (auto_timeout > 0 and started_at is not None
                    and now - started_at > auto_timeout):
                stale[rank] = ("never emitted a liveness beat "
                               f"({now - started_at:.1f}s since launch)")
                continue
        try:
            page = now - os.stat(prog).st_mtime
            if progress_timeout > 0 and page > progress_timeout:
                stale[rank] = f"no training progress for {page:.1f}s"
        except OSError:
            pass   # never opted in
    return stale


# -- named beats (serving replicas & other non-rank participants) ------------
#
# The rank-keyed files above serve elastic TRAINING; the elastic SERVING
# controller (fleet/elastic.py run_serving) watches arbitrarily-NAMED
# participants — "replica3" is not a trainer rank. Same transport, same
# staleness semantics, name-keyed files.

def touch_named(dir_path: str, name: str, payload: Optional[dict] = None):
    """One liveness beat for a named participant (``<name>.alive``)."""
    os.makedirs(dir_path, exist_ok=True)
    _touch(os.path.join(dir_path, f"{name}{_AUTO_SUFFIX}"),
           payload or {"t": time.time()})


def start_named(dir_path: str, name: str,
                interval: float = 1.0) -> threading.Event:
    """Auto-beat daemon for a named participant; returns the stop
    event. The thread dies with the process — a kill -9'd replica goes
    stale within ``interval`` + the watcher's timeout."""
    os.makedirs(dir_path, exist_ok=True)
    stop = threading.Event()
    path = os.path.join(dir_path, f"{name}{_AUTO_SUFFIX}")

    def loop():
        while not stop.is_set():
            try:
                _touch(path)
            except OSError:
                pass
            stop.wait(interval)

    threading.Thread(target=loop, daemon=True).start()
    return stop


def stale_names(dir_path: str, names, timeout: float,
                started_at=None) -> Dict[str, str]:
    """{name: reason} for every stale named participant. Same contract
    as :func:`check_stale`'s auto-beat leg: a participant that never
    beat is stale only once its startup grace (one ``timeout`` from
    ``started_at``) is spent. ``started_at`` may be a single float or
    a {name: float} map (per-replica spawn times). A beat file OLDER
    than ``started_at`` is a leftover from a previous incarnation of
    the name (controllers reuse replica0, replica1, ...) and counts as
    never-beat — a fresh healthy replica must get its startup grace,
    not be declared stale off a predecessor's mtime."""
    now = time.time()
    stale: Dict[str, str] = {}
    for name in names:
        path = os.path.join(dir_path, f"{name}{_AUTO_SUFFIX}")
        t0 = started_at.get(name) if isinstance(started_at, dict) \
            else started_at
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            mtime = None
        if mtime is not None and (t0 is None or mtime >= t0):
            age = now - mtime
            if timeout > 0 and age > timeout:
                stale[name] = f"no liveness beat for {age:.1f}s"
        elif timeout > 0 and t0 is not None and now - t0 > timeout:
            stale[name] = ("never emitted a liveness beat "
                           f"({now - t0:.1f}s since spawn)")
    return stale


# -- KV-store transport (multi-host, no shared filesystem) -------------------

class KVHeartbeatWatcher:
    """Staleness over KV beats, clock-skew-free: a rank's age is the
    time since its beat VALUE last changed, measured on THIS process's
    clock (cross-host timestamps are never compared — the etcd-lease
    property the reference relies on)."""

    def __init__(self, client=None):
        self._client = client if client is not None else _kv_client()
        # key -> (last value, local time the value last changed)
        self._last: Dict[str, tuple] = {}

    def _age(self, key: str, now: float) -> Optional[float]:
        try:
            val = self._client.key_value_try_get(key)
        except Exception:
            return None                 # never published
        prev = self._last.get(key)
        if prev is None or prev[0] != val:
            self._last[key] = (val, now)
            return 0.0
        return now - prev[1]

    def check(self, ranks, auto_timeout: float, progress_timeout: float,
              started_at: Optional[float] = None) -> Dict[int, str]:
        """Same contract as ``check_stale``, over the KV transport."""
        now = time.time()
        stale: Dict[int, str] = {}
        for rank in ranks:
            age = self._age(f"{_KV_PREFIX}/auto/rank{rank}", now)
            if age is None:
                if (auto_timeout > 0 and started_at is not None
                        and now - started_at > auto_timeout):
                    stale[rank] = ("never published a liveness beat "
                                   f"({now - started_at:.1f}s since "
                                   "launch)")
                continue
            if auto_timeout > 0 and age > auto_timeout:
                stale[rank] = f"no liveness beat for {age:.1f}s"
                continue
            page = self._age(f"{_KV_PREFIX}/progress/rank{rank}", now)
            if page is not None and progress_timeout > 0 \
                    and page > progress_timeout:
                stale[rank] = f"no training progress for {page:.1f}s"
        return stale

    def latest(self, kind: str, rank: int) -> Optional[dict]:
        try:
            return json.loads(self._client.key_value_try_get(
                f"{_KV_PREFIX}/{kind}/rank{rank}"))
        except Exception:
            return None


def start_kv_relay(dir_path: str, world_ranks, interval: float = 1.0,
                   client=None) -> Optional[threading.Event]:
    """Rank-0 side: mirror every rank's KV beats into ``dir_path`` as
    the files the launch controller already watches, so a controller
    with no shared filesystem (and no coordination client of its own)
    sees remote hosts' liveness through its local disk. A rank's file
    is touched only when its KV beat VALUE changes, preserving the
    staleness signal. Returns the stop event (None if no client)."""
    watcher = KVHeartbeatWatcher(client)
    if watcher._client is None:
        return None
    os.makedirs(dir_path, exist_ok=True)
    stop = threading.Event()
    seen: Dict[str, str] = {}

    def loop():
        while not stop.is_set():
            for rank in world_ranks:
                for kind, suffix in (("auto", _AUTO_SUFFIX),
                                     ("progress", _PROGRESS_SUFFIX)):
                    key = f"{_KV_PREFIX}/{kind}/rank{rank}"
                    try:
                        val = watcher._client.key_value_try_get(key)
                    except Exception:
                        continue
                    if seen.get(key) == val:
                        continue
                    seen[key] = val
                    try:
                        _touch(os.path.join(
                            dir_path, f"rank{rank}{suffix}"),
                            json.loads(val))
                    except (OSError, ValueError):
                        pass
            stop.wait(interval)

    threading.Thread(target=loop, daemon=True).start()
    return stop
