"""Worker liveness heartbeats for elastic training.

Reference capability: distributed/fleet/elastic/manager.py — etcd-lease
heartbeats give the elastic manager a membership signal, so a wedged or
silently-dead worker is detected, not just a crashed one. TPU-native
redesign: one controller per host (launch/main.py) watches per-rank
heartbeat FILES (mtime = last beat) — no external etcd; the transport is
the shared filesystem the launcher already owns for worker logs. (A
multi-host deployment can point PADDLE_HEARTBEAT_DIR at shared storage;
the beats are tiny O(ranks) touches.)

Two beat sources, two failure classes:
- AUTO beats: a daemon thread touches the file every interval — detects
  dead/killed/deadlocked-at-exec processes (the thread dies with them).
- PROGRESS beats: the training loop calls ``beat(step=n)`` — detects
  WEDGED-BUT-ALIVE workers (hung collective, stuck IO), which auto
  beats cannot see. The watcher uses the progress threshold only for
  workers that have opted in by emitting at least one progress beat.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

_AUTO_SUFFIX = ".alive"
_PROGRESS_SUFFIX = ".progress"
_state = {"thread": None, "stop": None, "dir": None, "rank": None}


def _touch(path, payload=None):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload or {"t": time.time()}))
    os.replace(tmp, path)


def start(dir_path: Optional[str] = None, rank: Optional[int] = None,
          interval: float = 1.0):
    """Start the auto-beat daemon thread (idempotent). Called by
    init_parallel_env when PADDLE_HEARTBEAT_DIR is set."""
    dir_path = dir_path or os.environ.get("PADDLE_HEARTBEAT_DIR")
    if not dir_path:
        return False
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    if _state["thread"] is not None and _state["thread"].is_alive():
        return True
    os.makedirs(dir_path, exist_ok=True)
    stop = threading.Event()
    path = os.path.join(dir_path, f"rank{rank}{_AUTO_SUFFIX}")

    def loop():
        while not stop.is_set():
            try:
                _touch(path)
            except OSError:
                pass
            stop.wait(interval)

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    _state.update(thread=th, stop=stop, dir=dir_path, rank=rank)
    return True


def stop():
    if _state["stop"] is not None:
        _state["stop"].set()
        _state["thread"] = None


def beat(step: Optional[int] = None):
    """Emit a PROGRESS beat from the training loop. A worker that emits
    one opts into wedge detection: the watcher kills the job if its
    progress beat goes stale."""
    dir_path = _state["dir"] or os.environ.get("PADDLE_HEARTBEAT_DIR")
    if not dir_path:
        return
    rank = _state["rank"] if _state["rank"] is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    os.makedirs(dir_path, exist_ok=True)
    _touch(os.path.join(dir_path, f"rank{rank}{_PROGRESS_SUFFIX}"),
           {"t": time.time(), "step": step})


def check_stale(dir_path: str, ranks, auto_timeout: float,
                progress_timeout: float,
                started_at: Optional[float] = None) -> Dict[int, str]:
    """Watcher side: {rank: reason} for every stale worker among
    ``ranks`` (GLOBAL rank ids — a node's watcher passes its own ranks,
    node_rank*nproc..+nproc). A rank with no auto beat yet is stale only
    once ``started_at`` is more than auto_timeout old (a worker can
    wedge before its first beat — import hang, stuck backend init);
    progress staleness applies only to ranks that have beaten progress
    at least once."""
    now = time.time()
    stale = {}
    for rank in ranks:
        auto = os.path.join(dir_path, f"rank{rank}{_AUTO_SUFFIX}")
        prog = os.path.join(dir_path, f"rank{rank}{_PROGRESS_SUFFIX}")
        try:
            age = now - os.stat(auto).st_mtime
            if auto_timeout > 0 and age > auto_timeout:
                stale[rank] = f"no liveness beat for {age:.1f}s"
                continue
        except OSError:
            # never beat at all: stale once the startup grace (one
            # auto_timeout from job start) is spent
            if (auto_timeout > 0 and started_at is not None
                    and now - started_at > auto_timeout):
                stale[rank] = ("never emitted a liveness beat "
                               f"({now - started_at:.1f}s since launch)")
                continue
        try:
            page = now - os.stat(prog).st_mtime
            if progress_timeout > 0 and page > progress_timeout:
                stale[rank] = f"no training progress for {page:.1f}s"
        except OSError:
            pass   # never opted in
    return stale
