"""One auto-tuner trial: a REAL training-step measurement in a fresh
process (reference: auto_tuner/tuner.py:21 + utils.py gen_new_args —
there each trial launches a distributed training script; here the trial
jits a sharded Llama train step over a virtual device mesh sized
dp*sharding*mp and times steady-state steps).

Run as:  python -m paddle_tpu.distributed.auto_tuner.trial '<cfg json>'
Prints ONE json line: {"ok": bool, "time": sec_per_step|null,
"tokens_per_sec": ..., "error": ...}.
"""
import json
import os
import sys
from ...core import enforce as E


def _configure_env(cfg):
    if cfg.get("pp_degree", 1) != 1:
        raise E.InvalidArgumentError(
            "trial runner measures dp x sharding x mp meshes only; "
            "prune pp_degree>1 from the search space (pipeline trials "
            "need the pipeline runtime, not a flat mesh)")
    n = cfg["dp_degree"] * cfg["sharding_degree"] * cfg["mp_degree"]
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    return n


def run(cfg, model_cfg):
    n = _configure_env(cfg)
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.models import llama as L

    devs = jax.devices()
    if len(devs) < n:
        return {"ok": False, "time": None,
                "error": f"need {n} devices, have {len(devs)}"}
    dp = cfg["dp_degree"] * cfg["sharding_degree"]
    mesh = Mesh(np.array(devs[:n]).reshape(
        cfg["dp_degree"], cfg["sharding_degree"], cfg["mp_degree"]),
        ("dp", "fsdp", "tp"))

    mcfg = L.llama_tiny(
        num_hidden_layers=int(model_cfg.get("num_layers", 2)),
        hidden_size=int(model_cfg.get("hidden_size", 64)),
        intermediate_size=int(model_cfg.get("intermediate_size", 128)),
        vocab_size=int(model_cfg.get("vocab_size", 256)),
        remat=bool(cfg.get("use_recompute", False)))
    seq = int(model_cfg.get("seq_len", 32))
    batch = cfg["micro_batch_size"] * dp

    params = L.shard_params(
        L.init_params(mcfg, jax.random.PRNGKey(0)), mcfg, mesh)
    # guard=False: trials rank UNGUARDED step throughput; the
    # sentinel gate is a constant additive cost, not a tuning axis
    step = L.make_train_step(mcfg, mesh, lr=1e-3, donate=False,
                             guard=False)
    ids = jax.device_put(
        jnp.asarray(np.random.default_rng(0).integers(
            0, mcfg.vocab_size, (batch, seq + 1)), jnp.int32),
        NamedSharding(mesh, P(("dp", "fsdp"), None)))

    ost = L.adamw_init(params)
    params, ost, loss = step(params, ost, ids)   # compile + warmup
    float(loss)
    iters = int(os.environ.get("TUNER_TRIAL_ITERS", "3"))
    t0 = time.perf_counter()
    for _ in range(iters):
        params, ost, loss = step(params, ost, ids)
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    # normalize to time per GLOBAL batch: a small micro-batch needs
    # acc_steps x more steps for the same work, so raw per-step dt would
    # systematically favor it
    acc = int(cfg.get("acc_steps", 1))
    # throughput is accumulation-invariant (tokens and time both scale
    # by acc); only the per-global-batch "time" carries the acc factor
    return {"ok": True, "time": round(dt * acc, 5),
            "tokens_per_sec": round(batch * seq / dt, 1),
            "error": None}


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    payload = json.loads(argv[0])
    try:
        out = run(payload["cfg"], payload.get("model_cfg", {}))
    except Exception as e:   # the parent needs a parseable line, always
        out = {"ok": False, "time": None,
               "error": f"{type(e).__name__}: {e}"[:500]}
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
