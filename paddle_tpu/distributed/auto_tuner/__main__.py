"""End-to-end auto-tune run: generate -> prune -> launch real trials ->
CSV history + best-config report.

    python -m paddle_tpu.distributed.auto_tuner [--max-trials N]
        [--out-dir DIR] [--devices N]

(reference: `python -m paddle.distributed.launch --auto_tuner_json ...`
driving auto_tuner/tuner.py; here the trials are sharded virtual-mesh
train steps so the search runs anywhere, chip or not.)
"""
import argparse
import json
import os
import sys

from . import AutoTuner, run_trial_subprocess, write_history_csv
from ...core import enforce as E


def main(argv=None):
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.auto_tuner")
    p.add_argument("--max-trials", type=int, default=6)
    p.add_argument("--out-dir", default="auto_tuner_out")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device budget per trial")
    p.add_argument("--trial-timeout", type=float, default=300.0)
    args = p.parse_args(argv)

    tuner_cfg = {
        "search_space": {
            "dp_degree": "auto", "sharding_degree": "auto",
            "mp_degree": "auto", "pp_degree": [1],
            "micro_batch_size": [1, 2, 4],
            "use_recompute": [False, True],
        },
        "num_gpus": args.devices,
        "global_batch_size": 8,   # top-level: generate_candidates reads
                                  # it here for acc_steps/mbs pruning
        "model_cfg": {"num_layers": 2, "hidden_size": 64,
                      "intermediate_size": 128, "vocab_size": 256,
                      "seq_len": 32},
    }
    tuner = AutoTuner(tuner_cfg)
    print(f"{len(tuner.candidates)} candidates after pruning",
          file=sys.stderr)

    def run_fn(cfg):
        rec = run_trial_subprocess(cfg, tuner_cfg,
                                   timeout=args.trial_timeout)
        cfg["tokens_per_sec"] = rec.get("tokens_per_sec")
        cfg["error"] = rec.get("error")
        print(f"trial dp={cfg['dp_degree']} sh={cfg['sharding_degree']} "
              f"mp={cfg['mp_degree']} mbs={cfg['micro_batch_size']} "
              f"rc={cfg.get('use_recompute')} -> {rec}", file=sys.stderr)
        if not rec.get("ok"):
            raise E.PreconditionNotMetError(rec.get("error") or "trial failed")
        return rec["time"]

    best = tuner.tune(run_fn, max_trials=args.max_trials)
    os.makedirs(args.out_dir, exist_ok=True)
    csv_path = os.path.join(args.out_dir, "history.csv")
    write_history_csv(tuner.history, csv_path)
    report = {"best": best, "trials": len(tuner.history),
              "history_csv": csv_path}
    with open(os.path.join(args.out_dir, "best_cfg.json"), "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return 0 if best else 1


if __name__ == "__main__":
    sys.exit(main())
