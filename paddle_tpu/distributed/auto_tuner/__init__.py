"""paddle.distributed.auto_tuner parity: parallel-config search.

Reference capability: python/paddle/distributed/auto_tuner/{tuner.py:21
AutoTuner (search_once/add_cfg loop), prune.py (prune_by_mp/pp/mbs/
sharding), search.py GridSearch, recorder.py}. TPU-native redesign: the
candidate space is factorizations dp*mp*pp*sharding == num chips with
micro-batch divisors; pruning uses an analytic HBM model (params/optimizer
state sharded per axis + activation bytes per microbatch) against the
chip's HBM budget, plus the reference's heuristic rules (mp within a
host's chip count, pp dividing layers). The measurement loop is caller-
driven exactly like the reference: search_once() -> run trial -> add_cfg.
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional

__all__ = ["AutoTuner", "generate_candidates", "estimate_memory_bytes",
           "prune_by_memory", "default_cost"]


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(tuner_cfg: Dict) -> List[Dict]:
    """All dp/mp/pp/sharding factorizations of world size × micro-batch
    divisors (reference: search.py GridSearch.all_tasks over the same
    dimension lists)."""
    world = int(tuner_cfg.get("num_gpus", tuner_cfg.get("num_chips", 8)))
    gbs = int(tuner_cfg.get("global_batch_size", 8))
    mp_cands = tuner_cfg.get("mp_degree", "auto")
    pp_cands = tuner_cfg.get("pp_degree", "auto")
    dp_cands = tuner_cfg.get("dp_degree", "auto")
    sh_cands = tuner_cfg.get("sharding_degree", "auto")
    mbs_cands = tuner_cfg.get("micro_batch_size", "auto")

    def cand(spec):
        return _divisors(world) if spec in ("auto", None) else \
            [int(v) for v in spec]

    out = []
    for mp, pp, dp, sh in itertools.product(
            cand(mp_cands), cand(pp_cands), cand(dp_cands), cand(sh_cands)):
        if mp * pp * dp * sh != world:
            continue
        # dp AND sharding both split the batch (reference prune_by_mbs
        # divides the global batch by dp*sharding)
        dp_ways = max(dp * sh, 1)
        if gbs % dp_ways != 0:
            continue
        local_bs = gbs // dp_ways
        for mbs in (_divisors(local_bs) if mbs_cands in ("auto", None)
                    else [int(v) for v in mbs_cands]):
            if local_bs % mbs != 0:
                continue
            out.append({"dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                        "sharding_degree": sh, "sharding_stage":
                        int(tuner_cfg.get("sharding_stage", 1)),
                        "micro_batch_size": mbs,
                        "acc_steps": local_bs // mbs,
                        "use_recompute":
                        bool(tuner_cfg.get("use_recompute", False))})
    return out


def estimate_memory_bytes(cfg: Dict, model_cfg: Dict) -> float:
    """Analytic per-chip HBM model (reference: prune.py prune_by_memory's
    estimated usage): sharded params + grads + optimizer moments +
    activation bytes for one microbatch through the local pp stage."""
    n_params = float(model_cfg.get("num_params", 1e9))
    layers = int(model_cfg.get("num_layers", 32))
    hidden = int(model_cfg.get("hidden_size", 4096))
    seq = int(model_cfg.get("seq_length", 2048))
    bytes_per = 2.0 if model_cfg.get("dtype", "bfloat16") in (
        "bfloat16", "float16") else 4.0

    mp, pp, sh = cfg["mp_degree"], cfg["pp_degree"], cfg["sharding_degree"]
    stage = cfg.get("sharding_stage", 1)
    local_params = n_params / (mp * pp)
    param_b = local_params * bytes_per
    if stage >= 3:
        param_b /= sh
    grad_b = local_params * bytes_per / (sh if stage >= 2 else 1)
    # master weights + two Adam moments in fp32
    opt_b = local_params * 12.0 / sh
    # activation bytes ≈ mbs * seq * hidden * layers_local * c
    # (c≈18 for a transformer block without remat, ≈2 with full remat)
    c = 2.0 if cfg.get("use_recompute") else 18.0
    act_b = (cfg["micro_batch_size"] * seq * hidden
             * (layers / pp) * c * bytes_per / mp)
    # 1F1B keeps up to pp in-flight microbatch activations on stage 0
    act_b *= min(pp, cfg.get("acc_steps", 1))
    return param_b + grad_b + opt_b + act_b


def prune_by_memory(cands: List[Dict], tuner_cfg: Dict) -> List[Dict]:
    model_cfg = tuner_cfg.get("model_cfg", {})
    budget = float(tuner_cfg.get("max_mem_usage",
                                 tuner_cfg.get("hbm_bytes", 95e9)))
    kept = []
    for c in cands:
        est = estimate_memory_bytes(c, model_cfg)
        c["estimated_memory_bytes"] = est
        if est <= budget:
            kept.append(c)
    return kept


def _prune_heuristics(cands: List[Dict], tuner_cfg: Dict) -> List[Dict]:
    """The reference's rule pruners (prune_by_mp/pp): mp stays within one
    host's chips (ICI, not DCN); pp must divide the layer count."""
    chips_per_host = int(tuner_cfg.get("gpus_per_node",
                                       tuner_cfg.get("chips_per_host", 4)))
    layers = int(tuner_cfg.get("model_cfg", {}).get("num_layers", 32))
    out = []
    for c in cands:
        if c["mp_degree"] > chips_per_host:
            continue
        if layers % c["pp_degree"] != 0:
            continue
        out.append(c)
    return out


def default_cost(cfg: Dict, model_cfg: Dict) -> float:
    """Relative step-time model for ranking (lower is better): compute
    splits over dp*sh; mp pays all-reduce overhead; pp pays bubble
    (p-1)/m; small micro-batches under-utilize the MXU."""
    dp_ways = cfg["dp_degree"] * cfg["sharding_degree"]
    compute = 1.0 / (dp_ways * cfg["mp_degree"] * cfg["pp_degree"])
    mp_comm = 0.08 * (cfg["mp_degree"] - 1) / max(cfg["mp_degree"], 1) \
        * compute
    m = cfg["acc_steps"]
    bubble = (cfg["pp_degree"] - 1) / max(m, 1) * compute
    mxu_eff = min(1.0, cfg["micro_batch_size"] / 4.0) * 0.3 + 0.7
    recompute_cost = 1.33 if cfg.get("use_recompute") else 1.0
    return (compute + mp_comm + bubble) * recompute_cost / mxu_eff


class AutoTuner:
    """reference: tuner.py:21 — iterate candidate configs best-first;
    the caller measures each (launch a trial) and reports back."""

    def __init__(self, tuner_cfg: Dict):
        self.tuner_cfg = dict(tuner_cfg)
        cands = generate_candidates(self.tuner_cfg)
        cands = _prune_heuristics(cands, self.tuner_cfg)
        cands = prune_by_memory(cands, self.tuner_cfg)
        model_cfg = self.tuner_cfg.get("model_cfg", {})
        cands.sort(key=lambda c: default_cost(c, model_cfg))
        self._cands = cands
        self._idx = 0
        self.history: List[Dict] = []
        self.cur_task_id = 0

    @property
    def candidates(self) -> List[Dict]:
        return list(self._cands)

    def search_once(self) -> Optional[Dict]:
        """Next config to try, or None when exhausted."""
        if self._idx >= len(self._cands):
            return None
        cfg = self._cands[self._idx]
        self._idx += 1
        self.cur_task_id += 1
        return dict(cfg)

    def add_cfg(self, cfg: Dict):
        """Record a measured trial (cfg must carry the metric key,
        default 'time')."""
        self.history.append(dict(cfg))

    def get_best(self, metric: str = "time", mode: str = "min") -> Optional[Dict]:
        runs = [h for h in self.history if metric in h
                and h[metric] is not None]
        if not runs:
            return None
        pick = min if mode == "min" else max
        return pick(runs, key=lambda h: h[metric])

    def tune(self, run_fn: Callable[[Dict], float], max_trials: int = 0,
             metric: str = "time", mode: str = "min") -> Optional[Dict]:
        """Convenience measurement loop: run_fn(cfg) -> metric value
        (None/exception = failed trial, recorded and skipped)."""
        trials = 0
        while True:
            if max_trials and trials >= max_trials:
                break
            cfg = self.search_once()
            if cfg is None:
                break
            trials += 1
            try:
                val = run_fn(cfg)
            except Exception:
                val = None
            cfg[metric] = val
            self.add_cfg(cfg)
        return self.get_best(metric, mode)


def run_trial_subprocess(cfg: Dict, tuner_cfg: Dict,
                         timeout: float = 300.0) -> Dict:
    """Measure one config in a FRESH process (reference tuner launches a
    real distributed trial per config, tuner.py:21 / utils.py
    gen_new_args): the child builds a dp x sharding x mp virtual mesh
    and times a jitted sharded train step. Returns the child's JSON
    record ({"ok", "time", "tokens_per_sec", "error"})."""
    import json
    import os
    import subprocess
    import sys

    payload = json.dumps({"cfg": cfg,
                          "model_cfg": tuner_cfg.get("model_cfg", {})})
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = None
    try:
        r = subprocess.run(
            [sys.executable, "-m",
             "paddle_tpu.distributed.auto_tuner.trial", payload],
            capture_output=True, text=True, timeout=timeout, env=env)
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        return json.loads(line)
    except Exception as e:
        err = f"trial runner: {type(e).__name__}: {e}"
        stderr = getattr(e, "stderr", None)   # TimeoutExpired carries it
        if r is not None:
            err += f" [rc={r.returncode}]"
            stderr = r.stderr
        if stderr:   # keep the child's actual failure visible
            if isinstance(stderr, bytes):
                stderr = stderr.decode(errors="replace")
            err += f" stderr: ...{stderr[-400:]}"
        return {"ok": False, "time": None, "error": err[:900]}


def write_history_csv(history: List[Dict], path: str) -> None:
    """Trial history as CSV (reference: recorder.py RecordTable
    store_history)."""
    import csv

    keys: List[str] = []
    for h in history:
        for k in h:
            if k not in keys:
                keys.append(k)
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=keys)
        w.writeheader()
        for h in history:
            w.writerow(h)
