"""Pipeline parallelism — TPU-native staged execution.

Reference capability: fleet/meta_parallel/pipeline_parallel.py (1F1B
`forward_backward_pipeline:459`, interleaved `:1008`) + the FleetExecutor
actor runtime (fleet_executor.h:36) + P2P layer (p2p_communication.py).

TPU-native design: XLA has no native pipeline parallelism, so the schedule
is built *inside one jitted program* as a collective-permute pipeline over a
mesh axis (SURVEY.md §7 "PP" row): every device holds one stage's weights
(stacked leading axis sharded over 'pp'), and a `lax.scan` over
`num_micro + num_stages - 1` ticks shifts activations stage-to-stage with
`lax.ppermute` (ICI collective-permute — the p2p primitive). Stage 0
injects a fresh micro-batch each tick; the last stage emits into the output
buffer. Differentiating the scanned program yields the reversed pipeline
(backward micro-batch schedule) automatically — GPipe semantics with
per-stage rematerialisation bounding activation memory.

This module is the fully-compiled homogeneous-stage pipeline. The general
schedule family — 1F1B, interleaved VPP, zero-bubble, heterogeneous
embedding/head stages — lives in fleet/pipeline_schedules.py (schedules as
data) + fleet/pipeline_runtime.py (the stage-program interpreter).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from ..core.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["pipeline_spmd", "make_pipeline_train_step",
           "shard_stage_params", "split_microbatches"]


def split_microbatches(batch, num_micro: int):
    """[B, ...] -> [M, B/M, ...]."""
    return jax.tree.map(
        lambda x: x.reshape(num_micro, x.shape[0] // num_micro,
                            *x.shape[1:]), batch)


def pipeline_spmd(stage_fn: Callable, params, micro_inputs, mesh: Mesh,
                  *, axis: str = "pp", remat: bool = True):
    """Run a GPipe collective-permute pipeline over mesh axis ``axis``.

    stage_fn(stage_params, x) -> y, same activation shape in/out (the
    classic homogeneous-stage transformer assumption).
    params: pytree with leading axis = num_stages (sharded over ``axis``).
    micro_inputs: [M, mb, ...] micro-batched activations (replicated).
    Returns [M, mb, ...] outputs of the final stage.
    """
    num_stages = mesh.shape[axis]
    num_micro = jax.tree.leaves(micro_inputs)[0].shape[0]
    ticks = num_micro + num_stages - 1

    fn = jax.checkpoint(stage_fn, prevent_cse=False) if remat else stage_fn

    def per_device(stage_params, micros):
        # stage_params: [1, ...] slice for this device; micros: full [M,...]
        stage_params = jax.tree.map(lambda p: p[0], stage_params)
        sid = lax.axis_index(axis)
        zero = jax.tree.map(lambda x: jnp.zeros_like(x[0]), micros)
        outputs = jax.tree.map(
            lambda x: jnp.zeros_like(x), micros)

        def tick(carry, t):
            state, outputs = carry
            # receive previous stage's activation (ring shifted by one)
            perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
            shifted = jax.tree.map(
                lambda s: lax.ppermute(s, axis, perm), state)
            # stage 0 ingests micro-batch t (or zeros when drained)
            inject = jax.tree.map(
                lambda m, z: jnp.where(t < num_micro, m[jnp.minimum(
                    t, num_micro - 1)], z), micros, zero)
            x = jax.tree.map(
                lambda inj, sh: jnp.where(sid == 0, inj, sh),
                inject, shifted)
            y = fn(stage_params, x)
            # last stage emits micro-batch index t - (S-1)
            out_idx = jnp.clip(t - (num_stages - 1), 0, num_micro - 1)
            emit = (sid == num_stages - 1) & (t >= num_stages - 1)
            outputs = jax.tree.map(
                lambda buf, yy: lax.dynamic_update_index_in_dim(
                    buf, jnp.where(emit, yy, buf[out_idx]), out_idx, 0),
                outputs, y)
            return (y, outputs), None

        (last, outputs), _ = lax.scan(
            tick, (zero, outputs), jnp.arange(ticks))
        # outputs live on the last stage; broadcast to all (psum of the
        # one non-zero contribution)
        outputs = jax.tree.map(
            lambda o: lax.psum(
                jnp.where(sid == num_stages - 1, o, jnp.zeros_like(o)),
                axis), outputs)
        return outputs

    pspec = jax.tree.map(lambda _: P(axis), params)
    return shard_map(
        per_device, mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check_vma=False,
    )(params, micro_inputs)


def make_pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                             mesh: Mesh, *, num_micro: int,
                             axis: str = "pp", lr: float = 1e-3,
                             remat: bool = True):
    """Jitted pipeline-parallel SGD train step.

    stage_fn(stage_params, x) -> y; loss_fn(y, labels) -> scalar (applied
    to final-stage output per micro-batch, averaged).
    Returns step(params, batch, labels) -> (params, loss), with params'
    leading axis sharded over the pp mesh axis.
    """

    def loss_of(params, batch, labels):
        micro_x = split_microbatches(batch, num_micro)
        micro_y = pipeline_spmd(stage_fn, params, micro_x, mesh,
                                axis=axis, remat=remat)
        micro_l = split_microbatches(labels, num_micro)
        losses = jax.vmap(loss_fn)(micro_y, micro_l)
        return jnp.mean(losses)

    def step(params, batch, labels):
        loss, grads = jax.value_and_grad(loss_of)(params, batch, labels)
        params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                              params, grads)
        return params, loss

    return jax.jit(step)


def shard_stage_params(params, mesh: Mesh, axis: str = "pp"):
    """Place stage-stacked params (leading axis = stages) on the pp axis."""
    return jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(mesh, P(axis))), params)
