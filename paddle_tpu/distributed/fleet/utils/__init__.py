"""fleet.utils parity surface (reference:
python/paddle/distributed/fleet/utils/__init__.py — recompute re-export)."""
from ..recompute import recompute, recompute_sequential  # noqa
