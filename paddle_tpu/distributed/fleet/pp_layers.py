"""PipelineLayer — the user-facing pipeline model description.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py
(`PipelineLayer:257`, `SegmentLayers:92`, LayerDesc/SharedLayerDesc).
TPU-native notes: segmentation (uniform or parameter-weighted) is identical
in spirit; execution differs — instead of per-rank processes exchanging
activations over NCCL p2p, `PipelineLayer` (a) runs all stages in-process
for eager/debug use and (b) exports per-stage callables that
distributed.pipeline.pipeline_spmd schedules as one collective-permute
program over the 'pp' mesh axis.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

from ...nn.layer.base import Layer
from ...core import enforce as E

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    """Deferred layer construction (reference pp_layers.py LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        if not issubclass(layer_cls, Layer):
            raise TypeError(
                f"LayerDesc expects a Layer subclass, got {layer_cls}")
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages (e.g. tied embeddings). On TPU the
    weight lives replicated (or tp-sharded) and both stages reference the
    same Parameter; the reference instead allreduces grads between the
    owning ranks."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layer descs into num_parts stages (reference
    SegmentLayers:92): uniform by count, or weighted by parameter count
    when method='parameter'."""

    def __init__(self, layers: Sequence, num_parts: int,
                 method: str = "uniform"):
        self.layers = layers
        self.num_parts = num_parts
        self.method = method
        if len(layers) < num_parts:
            raise E.InvalidArgumentError(
                f"cannot split {len(layers)} layers into {num_parts} parts")

    def do_segment(self) -> List[int]:
        n = len(self.layers)
        if self.method == "uniform":
            base, extra = divmod(n, self.num_parts)
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < extra else 0))
            return bounds
        if self.method.startswith("layer:"):
            # each stage *starts at* a layer of the named class (reference
            # "layer:Block" semantics — trailing non-named layers stay with
            # their preceding block)
            name = self.method.split(":", 1)[1]
            idx = [i for i, l in enumerate(self.layers)
                   if getattr(getattr(l, "layer_cls", type(l)),
                              "__name__", "") == name]
            if len(idx) < self.num_parts:
                raise E.InvalidArgumentError(
                    f"only {len(idx)} '{name}' layers for "
                    f"{self.num_parts} parts")
            per, extra = divmod(len(idx), self.num_parts)
            bounds = [0]
            taken = 0
            for i in range(self.num_parts - 1):
                taken += per + (1 if i < extra else 0)
                bounds.append(idx[taken])   # next part starts AT this block
            bounds.append(n)
            return bounds
        if self.method == "parameter":
            weights = []
            for l in self.layers:
                if isinstance(l, LayerDesc):
                    built = l.build_layer()
                    w = sum(p.numel() for p in built.parameters())
                elif isinstance(l, Layer):
                    w = sum(p.numel() for p in l.parameters())
                else:
                    w = 0
                weights.append(max(int(w), 1))
            total = sum(weights)
            bounds = [0]
            acc = 0
            remaining_parts = self.num_parts
            target = total / remaining_parts
            for i, w in enumerate(weights):
                layers_left = n - (i + 1)
                acc += w
                # close the part when it reaches the (re-balanced) target,
                # or when the remaining layers are only just enough to give
                # every remaining part at least one layer
                must_cut = layers_left == remaining_parts - 1
                if remaining_parts > 1 and (acc >= target or must_cut):
                    bounds.append(i + 1)
                    remaining_parts -= 1
                    total -= acc
                    acc = 0
                    target = total / max(remaining_parts, 1)
            bounds.append(n)
            return bounds
        raise E.InvalidArgumentError(f"unknown segment method {self.method}")


class PipelineLayer(Layer):
    """Reference pp_layers.py:257. Describes the whole model as a flat
    layer list, segments it into stages, builds only what this rank needs
    (here: builds all stages — single-controller SPMD — and exposes
    per-stage sublayers + run helpers)."""

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, **kwargs):
        super().__init__()
        self._descs = list(layers)
        if topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        seg = SegmentLayers(self._descs, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()

        self._shared = {}
        built: List[Layer] = []
        self.run_functions: List[Any] = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                if d.forward_func is not None:
                    fwd = d.forward_func
                    layer_ref = layer
                    self.run_functions.append(
                        lambda x, l=layer_ref, f=fwd: f(l, x))
                else:
                    self.run_functions.append(layer)
                built.append(layer)
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                built.append(layer)
                self.run_functions.append(layer)
            elif isinstance(d, Layer):
                built.append(d)
                self.run_functions.append(d)
            elif callable(d):
                built.append(None)
                self.run_functions.append(d)
            else:
                raise TypeError(f"bad pipeline item {d!r}")
        # register built layers so parameters() walks them
        for i, l in enumerate(built):
            if l is not None:
                self.add_sublayer(str(i), l)

    # -- introspection ----------------------------------------------------
    def get_num_stages(self) -> int:
        return self._num_stages

    def stage_slices(self):
        return [(self.segment_parts[i], self.segment_parts[i + 1])
                for i in range(self._num_stages)]

    def get_stage_layers(self, stage_id: int):
        lo, hi = self.stage_slices()[stage_id]
        return self.run_functions[lo:hi]

    def stage_callable(self, stage_id: int) -> Callable:
        """The stage as a plain callable activation -> activation."""
        fns = self.get_stage_layers(stage_id)

        def run(x):
            for f in fns:
                x = f(x)
            return x
        return run

    def forward(self, x):
        """Eager full-model forward (all stages in-process)."""
        for f in self.run_functions:
            x = f(x)
        return x

    @property
    def loss_fn(self):
        return self._loss_fn
