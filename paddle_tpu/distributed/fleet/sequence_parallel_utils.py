"""Sequence parallelism utilities (Megatron-SP parity).

Reference: fleet/utils/sequence_parallel_utils.py — ScatterOp/GatherOp/
AllGatherOp/ReduceScatterOp PyLayers (:85-147), ColumnSequenceParallelLinear
(:395), RowSequenceParallelLinear (:528).

TPU-native: under GSPMD the scatter/gather pair is a *sharding constraint*
on the sequence dim — XLA materialises the all-gather before a TP matmul
and the reduce-scatter after it, overlapping with compute (the hand overlap
of SPInnerOverlapLinear:240 comes free from the XLA scheduler). The op
classes below keep the reference's API: in eager single-process they are
identity-like views over the full sequence; inside a jitted/sharded program
they emit with_sharding_constraint on the seq dim of the 'mp' axis. The
explicit-collective forms (used inside shard_map) live in
distributed.comm_ops (all_gather/reduce_scatter).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ... import nn
from ...core.tensor import Tensor

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear"]


def _current_mesh():
    """The global ProcessMesh set by fleet.init / dist.auto_parallel."""
    from ..process_mesh import get_mesh
    return get_mesh()


from ...ops._op import op_fn


@op_fn(name="sp_sharding_constraint")
def _constraint_op(x, *, sharding):
    # differentiable: vjp of with_sharding_constraint is the constraint
    # itself, recorded on the tape like every other op
    return lax.with_sharding_constraint(x, sharding)


def _seq_constraint(x, shard: bool, seq_axis: int = 1):
    """Annotate the sequence dim as mp-sharded (scatter) or replicated
    (gather). Outside a mesh context this is the identity."""
    mesh = _current_mesh()
    if mesh is None:
        return x
    jm = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    if "mp" not in jm.axis_names:
        return x
    raw = x._data if isinstance(x, Tensor) else x
    spec = [None] * raw.ndim
    if shard:
        spec[seq_axis] = "mp"
    sharding = NamedSharding(jm, P(*spec))
    try:
        if isinstance(x, Tensor):
            return _constraint_op(x, sharding=sharding)
        return lax.with_sharding_constraint(raw, sharding)
    except Exception:   # not under jit / device mismatch: plain identity
        return x


def _feature_constraint(x, shard: bool):
    """Annotate the last (feature/head) dim as mp-sharded or replicated."""
    mesh = _current_mesh()
    if mesh is None:
        return x
    jm = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    if "mp" not in jm.axis_names:
        return x
    raw = x._data if isinstance(x, Tensor) else x
    spec = [None] * raw.ndim
    if shard:
        spec[-1] = "mp"
    sharding = NamedSharding(jm, P(*spec))
    try:
        if isinstance(x, Tensor):
            return _constraint_op(x, sharding=sharding)
        return lax.with_sharding_constraint(raw, sharding)
    except Exception:
        return x


class ScatterOp:
    """reference :85 — split activations along seq dim across mp ranks.
    GSPMD: a seq-dim sharding constraint."""

    @staticmethod
    def apply(x, axis: int = 1):
        return _seq_constraint(x, shard=True, seq_axis=axis)


class GatherOp:
    """reference :103 — gather seq-sharded activations back."""

    @staticmethod
    def apply(x, axis: int = 1):
        return _seq_constraint(x, shard=False, seq_axis=axis)


class AllGatherOp:
    """reference :121 — allgather along seq (fwd) / reduce-scatter (bwd)."""

    @staticmethod
    def apply(x, axis: int = 1):
        return _seq_constraint(x, shard=False, seq_axis=axis)


class ReduceScatterOp:
    """reference :138 — reduce-scatter along seq (fwd) / allgather (bwd)."""

    @staticmethod
    def apply(x, axis: int = 1):
        return _seq_constraint(x, shard=True, seq_axis=axis)


def mark_as_sequence_parallel_parameter(param):
    """reference :166 — tag params whose grads need the SP allreduce; under
    GSPMD replicated params already psum their grads, so this is metadata
    only (kept for API parity / checkpoint tooling)."""
    param.sequence_parallel = True
    return param


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """reference :192 — no-op on TPU: the grad allreduce the hooks issue is
    emitted by GSPMD from the sharding annotations."""
    return model


class ColumnSequenceParallelLinear(nn.Layer):
    """reference :395 — column-parallel linear whose input arrives
    seq-sharded: allgather(seq) → matmul with column-sharded weight.
    GSPMD expression: weight Shard(1) on mp; input constrained seq-sharded;
    output constrained head/feature-sharded."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, name=None, **kw):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        # reference :395: gather_output=True returns the full (replicated)
        # feature dim instead of leaving it mp-sharded
        self.gather_output = gather_output
        self._shard_weight()

    def _shard_weight(self):
        mesh = _current_mesh()
        if mesh is None:
            return
        from .. import api as dist_api
        from ..placement import Replicate, Shard
        jm = mesh
        try:
            nd = jm.ndim
            pl = [Replicate()] * nd
            pl[jm.dim_names.index("mp")] = Shard(1)
            t = dist_api.shard_tensor(self.linear.weight, jm, pl)
            self.linear.weight._data = t._data
        except Exception:
            pass

    def forward(self, x):
        x = AllGatherOp.apply(x)           # seq gathered before the matmul
        y = self.linear(x)
        if self.gather_output:
            y = _feature_constraint(y, shard=False)
        else:
            y = _feature_constraint(y, shard=True)
        return y


class RowSequenceParallelLinear(nn.Layer):
    """reference :528 — row-parallel linear whose output returns to the
    seq-sharded domain: matmul with row-sharded weight → reduce-scatter
    over seq."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, name=None, **kw):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self._shard_weight()

    def _shard_weight(self):
        mesh = _current_mesh()
        if mesh is None:
            return
        from .. import api as dist_api
        from ..placement import Replicate, Shard
        try:
            nd = mesh.ndim
            pl = [Replicate()] * nd
            pl[mesh.dim_names.index("mp")] = Shard(0)
            t = dist_api.shard_tensor(self.linear.weight, mesh, pl)
            self.linear.weight._data = t._data
        except Exception:
            pass

    def forward(self, x):
        y = self.linear(x)
        return ReduceScatterOp.apply(y)    # back to the seq-sharded domain
