"""Pipeline schedules: FThenB (GPipe), 1F1B, interleaved VPP, zero-bubble.

Reference capability:
- 1F1B: fleet/meta_parallel/pipeline_parallel.py:459 forward_backward_pipeline
- interleaved VPP: pipeline_parallel.py:1008 PipelineParallelWithInterleave
- zero-bubble: distributed/passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:37-106
  (splits backward into an input-grad job ``backward_b`` and a weight-grad
  job ``backward_w`` so weight grads fill the cooldown bubble)

TPU-native design: a schedule here is DATA — an ordered list of typed
actions per pipeline stage — consumed by the host-driven stage runtime
(pipeline_runtime.PipelineParallel), which executes each action as a cached
jitted stage program. This mirrors the reference's *static* scheduling
design (typed Job lists in a core.Plan run by StandaloneExecutor,
new_executor/interpreter/plan.h) rather than its dygraph hand-coded loops:
on TPU every unit of work should be a compiled program, and the schedule
should be an inspectable artifact.

Action kinds:
  F  — forward of one micro-batch through one stage-chunk
  B  — full backward (input grad + weight grad together)
  BI — backward input-grad only   (zero-bubble)
  BW — backward weight-grad only  (zero-bubble)

Positions: with virtual-pipeline chunks, stage ``s`` of ``S`` holds chunks
``c`` in 0..v-1; the model is cut into ``S*v`` parts and part index
``p = c*S + s`` (Megatron/reference assignment: consecutive model parts
round-robin over stages).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple
from ...core import enforce as E

__all__ = ["Action", "build_schedule", "fthenb", "one_f_one_b",
           "interleaved_1f1b", "zero_bubble_h1", "validate_schedule",
           "peak_live_activations"]


@dataclasses.dataclass(frozen=True)
class Action:
    kind: str       # F | B | BI | BW
    chunk: int      # virtual-pipeline chunk on this stage (0 if v == 1)
    micro: int      # micro-batch id

    def __repr__(self):
        return f"{self.kind}{self.chunk}.{self.micro}"


Schedule = List[List[Action]]   # [stage][ordered actions]


def fthenb(num_stages: int, num_micro: int) -> Schedule:
    """GPipe: all forwards, then all backwards (reverse order)."""
    sched = []
    for _s in range(num_stages):
        acts = [Action("F", 0, m) for m in range(num_micro)]
        acts += [Action("B", 0, m) for m in reversed(range(num_micro))]
        sched.append(acts)
    return sched


def one_f_one_b(num_stages: int, num_micro: int) -> Schedule:
    """1F1B (reference pipeline_parallel.py:459): per stage, a warmup of
    ``S - s - 1`` forwards, then steady-state alternating F/B, then a
    cooldown of the remaining backwards. Bounds live activations per stage
    to ``S - s`` instead of GPipe's ``num_micro``."""
    sched = []
    for s in range(num_stages):
        warmup = min(num_stages - s - 1, num_micro)
        acts: List[Action] = []
        f = b = 0
        for _ in range(warmup):
            acts.append(Action("F", 0, f)); f += 1
        while f < num_micro:
            acts.append(Action("F", 0, f)); f += 1
            acts.append(Action("B", 0, b)); b += 1
        while b < num_micro:
            acts.append(Action("B", 0, b)); b += 1
        sched.append(acts)
    return sched


def _vpp_chunk_micro(k: int, S: int, v: int) -> Tuple[int, int]:
    """Map iteration index -> (chunk, micro) for the interleaved schedule.

    Micro-batches advance in groups of S; within a group the same S micros
    pass through every chunk before the next group starts (the reference's
    get_model_chunk_id logic in PipelineParallelWithInterleave)."""
    kg = k % (S * v)
    chunk = kg // S
    group = k // (S * v)
    micro = group * S + (kg % S)
    return chunk, micro


def interleaved_1f1b(num_stages: int, num_micro: int,
                     num_chunks: int) -> Schedule:
    """Interleaved virtual-pipeline 1F1B (reference
    pipeline_parallel.py:1008). Each stage runs ``num_chunks`` model chunks;
    requires num_micro % num_stages == 0 (reference asserts the same)."""
    S, v = num_stages, num_chunks
    if v < 2:
        return one_f_one_b(num_stages, num_micro)
    if num_micro % S != 0:
        raise E.InvalidArgumentError(
            f"interleaved schedule requires num_micro ({num_micro}) to be "
            f"a multiple of num_stages ({S})")
    total = num_micro * v
    sched = []
    for s in range(S):
        warmup = min((S - s - 1) * 2 + (v - 1) * S, total)
        acts: List[Action] = []
        for k in range(warmup):
            c, m = _vpp_chunk_micro(k, S, v)
            acts.append(Action("F", c, m))
        for k in range(warmup, total):
            c, m = _vpp_chunk_micro(k, S, v)
            acts.append(Action("F", c, m))
            cb, mb = _vpp_chunk_micro(k - warmup, S, v)
            acts.append(Action("B", v - 1 - cb, mb))
        for k in range(total - warmup, total):
            cb, mb = _vpp_chunk_micro(k, S, v)
            acts.append(Action("B", v - 1 - cb, mb))
        sched.append(acts)
    return sched


def zero_bubble_h1(num_stages: int, num_micro: int) -> Schedule:
    """Zero-bubble ZB-H1 (reference pipeline_zero_bubble.py:37): 1F1B with
    the backward split into BI (input grad — on the critical path to the
    previous stage) and BW (weight grad — free to slide later). Each stage
    defers ``S - s - 1`` weight-grad jobs into its cooldown bubble, so the
    cooldown does useful work instead of idling. Peak stashed-input count
    rises to ~2*(S-s)-1 vs 1F1B's S-s (the BW job pins its stage input
    until it runs) — the H1 memory/bubble trade, asserted by
    tests/test_pipeline_schedules.py::test_memory_bounds.
    """
    S = num_stages
    sched = []
    for s in range(S):
        defer = min(S - s - 1, num_micro)
        warmup = min(S - s - 1, num_micro)
        acts: List[Action] = []
        f = bi = bw = 0
        for _ in range(warmup):
            acts.append(Action("F", 0, f)); f += 1
        while f < num_micro:
            acts.append(Action("F", 0, f)); f += 1
            acts.append(Action("BI", 0, bi)); bi += 1
            if bi - bw > defer:
                acts.append(Action("BW", 0, bw)); bw += 1
        while bi < num_micro:
            # cooldown: incoming BIs arrive one pipeline-cycle apart, leaving
            # slack for deferred W jobs in the gap BEFORE each next BI — this
            # is what makes the bubble "zero": W fills the idle wait instead
            # of trailing after the last BI
            for _ in range(2):
                if bw < bi:
                    acts.append(Action("BW", 0, bw)); bw += 1
            acts.append(Action("BI", 0, bi)); bi += 1
        while bw < num_micro:
            acts.append(Action("BW", 0, bw)); bw += 1
        sched.append(acts)
    return sched


_BUILDERS = {
    "FThenB": lambda S, M, v: fthenb(S, M),
    "1F1B": lambda S, M, v: one_f_one_b(S, M),
    "1F1B-Interleave": lambda S, M, v: interleaved_1f1b(S, M, v),
    "ZBH1": lambda S, M, v: zero_bubble_h1(S, M),
}


def build_schedule(name: str, num_stages: int, num_micro: int,
                   num_chunks: int = 1) -> Schedule:
    if name not in _BUILDERS:
        raise E.InvalidArgumentError(
            f"unknown schedule {name!r}; one of {sorted(_BUILDERS)}")
    return _BUILDERS[name](num_stages, num_micro, num_chunks)


# ---------------------------------------------------------------------------
# Static analysis (used by tests and by the runtime's deadlock check)
# ---------------------------------------------------------------------------

def validate_schedule(sched: Schedule, num_micro: int,
                      num_chunks: int = 1) -> None:
    """Check completeness + per-stage ordering constraints:
    every (chunk, micro) has exactly one F and one B (or BI+BW); BI before
    BW for the same unit; B/BI of a unit after its F on the same stage."""
    S = len(sched)
    for s, acts in enumerate(sched):
        seen: Dict[Tuple[str, int, int], int] = {}
        for i, a in enumerate(acts):
            key = (a.kind, a.chunk, a.micro)
            if key in seen:
                raise AssertionError(f"stage {s}: duplicate {a}")
            seen[key] = i
        for c in range(num_chunks):
            for m in range(num_micro):
                fi = seen.get(("F", c, m))
                if fi is None:
                    raise AssertionError(f"stage {s}: missing F{c}.{m}")
                if ("B", c, m) in seen:
                    if seen[("B", c, m)] < fi:
                        raise AssertionError(f"stage {s}: B{c}.{m} before F")
                else:
                    bi = seen.get(("BI", c, m))
                    bw = seen.get(("BW", c, m))
                    if bi is None or bw is None:
                        raise AssertionError(
                            f"stage {s}: missing backward for {c}.{m}")
                    if not (fi < bi < bw):
                        raise AssertionError(
                            f"stage {s}: bad BI/BW order for {c}.{m}")


def peak_live_activations(acts: List[Action]) -> int:
    """Max number of forward activations held before their backward frees
    them (the schedule's per-stage memory high-water mark; BW frees nothing
    — the weight-grad job keeps the stashed input until it runs)."""
    live = 0
    peak = 0
    for a in acts:
        if a.kind == "F":
            live += 1
            peak = max(peak, live)
        elif a.kind in ("B", "BW"):
            live -= 1
    return peak
