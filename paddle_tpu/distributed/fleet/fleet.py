"""fleet: the hybrid-parallel programming model entry point.

Reference: python/paddle/distributed/fleet/fleet.py (init:167,
_init_hybrid_parallel_env:599, distributed_model via fleet/model.py:32,
distributed_optimizer) and fleet/base/distributed_strategy.py (protobuf
DistributedStrategy, HybridConfig dp/mp/pp/sharding/sep degrees).

TPU-native: ``fleet.init`` builds the CommunicateTopology +
HybridCommunicateGroup over ONE global ProcessMesh (topology.py);
``distributed_model`` annotates rather than wraps — parameters get their
axis shardings (mp layers already carry them), inputs get dp-sharding via
shard_dataloader; ``distributed_optimizer`` applies sharding-stage placement
to optimizer states. The heavyweight per-mode wrapper classes of the
reference (TensorParallel/PipelineParallel/...) collapse because GSPMD
executes the parallelism the annotations describe.
"""
from __future__ import annotations

import os
from typing import Optional

from .. import env
from ..api import ShardingStage1, shard_optimizer
from ..process_mesh import set_mesh
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["DistributedStrategy", "init", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer", "worker_index",
           "worker_num", "is_first_worker", "Fleet"]

_hcg: Optional[HybridCommunicateGroup] = None
_strategy = None


class HybridConfig(dict):
    """dict with attribute access (parity with strategy.hybrid_configs)."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    """Reference: distributed_strategy.py (protobuf-backed). Plain attrs
    here; the protobuf indirection served C++ meta-optimizers we don't have."""

    def __init__(self):
        self.hybrid_configs = HybridConfig(
            dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
            sep_degree=1)
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False

    def __setattr__(self, k, v):
        if k == "hybrid_configs" and isinstance(v, dict) \
                and not isinstance(v, HybridConfig):
            cfg = HybridConfig(dp_degree=1, mp_degree=1, pp_degree=1,
                               sharding_degree=1, sep_degree=1)
            cfg.update(v)
            v = cfg
        object.__setattr__(self, k, v)


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """Reference: fleet.py:167 init → _init_hybrid_parallel_env:599."""
    global _hcg, _strategy
    env.init_parallel_env()
    strategy = strategy or DistributedStrategy()
    _strategy = strategy
    cfg = strategy.hybrid_configs
    dims = [cfg["dp_degree"], cfg["pp_degree"], cfg["sharding_degree"],
            cfg.get("sep_degree", 1), cfg["mp_degree"]]
    import jax
    n_needed = 1
    for d in dims:
        n_needed *= int(d)
    n_dev = len(jax.devices())
    if n_needed == 1 and n_dev > 1:
        # Degrees unset: default pure-DP over all devices (reference
        # defaults dp to world_size/others).
        dims[0] = n_dev
    topo = CommunicateTopology(dims=dims)
    _hcg = HybridCommunicateGroup(topo)
    set_mesh(_hcg.mesh)
    return _hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def distributed_model(model):
    """Reference: fleet/model.py:32 — wraps per parallel mode. Here the
    annotations on mp layers / dataloader already encode the parallelism;
    we only broadcast-replicate any un-annotated parameter onto the mesh so
    every param has a deliberate placement."""
    if _hcg is None:
        return model
    mesh = _hcg.mesh
    from ..api import shard_layer
    shard_fn = None  # default: replicate unannotated params

    def _fn(name, sublayer, m):
        from ..placement import Replicate
        from ..api import shard_tensor, _as_param
        for pname, p in list(sublayer._parameters.items()):
            if p is None or p._process_mesh is not None:
                continue
            rep = [Replicate() for _ in range(m.ndim)]
            sublayer._parameters[pname] = _as_param(shard_tensor(p, m, rep))

    shard_layer(model, mesh, _fn)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Reference: fleet.distributed_optimizer → HybridParallelOptimizer
    (hybrid_parallel_optimizer.py:255). Sharding degree > 1 applies ZeRO-1
    placement of optimizer states over the sharding axis."""
    st = strategy or _strategy
    if _hcg is not None and _hcg.get_sharding_parallel_world_size() > 1:
        return shard_optimizer(
            optimizer, ShardingStage1("sharding", _hcg.mesh))
    return optimizer


def worker_index() -> int:
    return env.get_rank()


def worker_num() -> int:
    return env.get_world_size()


def is_first_worker() -> bool:
    return env.get_rank() == 0


class Fleet:
    """Object-style facade (reference fleet.Fleet singleton)."""
    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    is_first_worker = staticmethod(is_first_worker)

    @property
    def hcg(self):
        return _hcg
