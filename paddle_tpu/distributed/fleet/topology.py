"""Hybrid-parallel topology: the nd process grid.

Reference: python/paddle/distributed/fleet/base/topology.py
(CommunicateTopology:65, HybridCommunicateGroup:178) — axis order
``[data, pipe, sharding, sep, model]``, one NCCL group per axis plus fused
groups.

TPU-native redesign: the five axes become named dims of ONE global
ProcessMesh (SURVEY.md §7: "fleet 5-axis topology → one Mesh with named
axes"). "Creating a comm group" costs nothing — an axis name is the group;
XLA compiles collectives over any axis subset. HybridCommunicateGroup keeps
the reference's query API (ranks/world-sizes per axis) so fleet code ports
over, and hands out the mesh for compiled paths.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import collective, env
from ..process_mesh import ProcessMesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

# Reference order (topology.py:65): data, pipe, sharding, sep, model.
_DEFAULT_ORDER = ["data", "pipe", "sharding", "sep", "model"]
# Mesh axis names used across the TPU build (models annotate against these).
AXIS_NAME = {"data": "dp", "pipe": "pp", "sharding": "sharding",
             "sep": "sep", "model": "mp"}


class CommunicateTopology:
    """An nd grid over ranks with named axes + coordinate queries."""

    def __init__(self, hybrid_group_names: Sequence[str] = _DEFAULT_ORDER,
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = [int(d) for d in dims]
        self._world = np.arange(int(np.prod(self._dims))).reshape(self._dims)

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(self._world.size)

    def get_rank(self, **axis_coords) -> int:
        coord = tuple(axis_coords[name] for name in self._parallel_names)
        return int(self._world[coord])

    def get_coord(self, rank: int):
        idx = np.argwhere(self._world == rank)[0]
        return tuple(int(i) for i in idx)

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on ``axis_name`` equals ``index``."""
        axis = self._parallel_names.index(axis_name)
        return [int(r) for r in np.take(self._world, index, axis=axis).flatten()]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Groups of ranks that vary only along ``axis_name`` (the reference's
        per-axis comm groups)."""
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._world, axis, -1)
        return [list(map(int, row)) for row in moved.reshape(-1, self._dims[axis])]

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = dict(zip(self._parallel_names, self.get_coord(global_rank)))
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    """Reference: topology.py:178. Query surface for each parallel axis plus
    the global ProcessMesh for compiled (GSPMD) paths."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = env.get_rank()
        self.nranks = topology.world_size()

        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") \
            if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = topology.get_dim("model")

        # One global mesh with the non-trivial axes, reference order.
        names, dims = [], []
        for ref_name in self._topo.get_hybrid_group_names():
            d = self._topo.get_dim(ref_name)
            names.append(AXIS_NAME[ref_name])
            dims.append(d)
        self.mesh = ProcessMesh(
            np.arange(int(np.prod(dims))).reshape(dims), names)

        # Per-axis groups (host-side handles; compiled comm uses axis names).
        self._groups: Dict[str, collective.Group] = {}
        for ref_name in self._topo.get_hybrid_group_names():
            ranks = self._ranks_of_my_group(ref_name)
            self._groups[ref_name] = collective.new_group(
                ranks, mesh_axis=AXIS_NAME[ref_name])

    def _ranks_of_my_group(self, axis_name: str) -> List[int]:
        for grp in self._topo.get_comm_list(axis_name):
            if self.global_rank in grp:
                return grp
        return [self.global_rank]

    def get_parallel_mode(self) -> str:
        """Reference: topology.py get_parallel_mode (ParallelMode)."""
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._sep_degree > 1:
            return "segment_parallel"
        if self._mp_degree > 1:
            return "tensor_parallel"
        return "data_parallel"

    def topology(self) -> CommunicateTopology:
        return self._topo

    def get_global_rank(self) -> int:
        return self.global_rank

    # -- per-axis queries (reference API names) -----------------------------
    def _axis_rank(self, name: str) -> int:
        coord = self._topo.get_coord(self.global_rank)
        return coord[self._topo.get_hybrid_group_names().index(name)]

    def get_data_parallel_rank(self) -> int:
        return self._axis_rank("data")

    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_model_parallel_rank(self) -> int:
        return self._axis_rank("model")

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_stage_id(self) -> int:
        return self._axis_rank("pipe")

    def get_pipe_parallel_rank(self) -> int:
        return self._axis_rank("pipe")

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def get_sharding_parallel_rank(self) -> int:
        return self._axis_rank("sharding")

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sep_parallel_rank(self) -> int:
        return self._axis_rank("sep") if self._sep_degree >= 1 else 0

    def get_sep_parallel_world_size(self) -> int:
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    def is_first_stage(self) -> bool:
        return self.get_stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.get_stage_id() == self._pp_degree - 1
