"""Megatron-style tensor-parallel layers.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(VocabParallelEmbedding:47, ColumnParallelLinear:334, RowParallelLinear:541,
ParallelCrossEntropy:742) and mp_ops.py identity/allreduce PyLayers.

TPU-native redesign (SURVEY.md §7: "TP/SP layers → GSPMD sharding
annotations"): instead of splitting weights into per-rank local shards and
hand-inserting allreduce/identity autograd ops, each layer stores the FULL
logical weight sharded over the ``mp`` mesh axis via NamedSharding:

  ColumnParallelLinear: W[in, out]  sharded Shard(1)  → y sharded on out dim
  RowParallelLinear:    W[in, out]  sharded Shard(0)  → partial-sum y; XLA
                        inserts the psum (the reference's allreduce) when the
                        consumer needs replicated values
  VocabParallelEmbedding: W[vocab, h] sharded Shard(0) → masked local lookup
                        + psum handled by XLA's gather partitioning

Forward math is the ordinary dense op on the global logical value — GSPMD
partitions it; there are no per-rank code paths, no PyLayer comm ops, and
the same layer runs 1-device or N-device unchanged. The grad allreduce the
reference does by hooks falls out of the partitioned backward.
"""
from __future__ import annotations

from typing import Optional

from ... import nn
from ...core.tensor import Parameter
from ...nn import functional as F
from ..api import shard_tensor
from ..placement import Replicate, Shard
from ..process_mesh import ProcessMesh, get_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _resolve_mesh(mesh: Optional[ProcessMesh]) -> Optional[ProcessMesh]:
    if mesh is not None:
        return mesh
    from . import fleet as _fleet
    hcg = _fleet._hcg
    if hcg is not None:
        return hcg.mesh
    return get_mesh()


def _mp_placements(mesh: ProcessMesh, axis: str, tensor_dim: int):
    placements = [Replicate() for _ in range(mesh.ndim)]
    if axis in mesh.dim_names:
        placements[mesh.dim_names.index(axis)] = Shard(tensor_dim)
    return placements


def _shard_param(param: Parameter, mesh: Optional[ProcessMesh], axis: str,
                 tensor_dim: int) -> Parameter:
    if mesh is None or axis not in mesh.dim_names:
        return param
    t = shard_tensor(param, mesh, _mp_placements(mesh, axis, tensor_dim))
    p = Parameter(t._data, name=param.name,
                  trainable=not param.stop_gradient)
    p._placements = t._placements
    p._process_mesh = t._process_mesh
    return p


class ColumnParallelLinear(nn.Layer):
    """Reference: mp_layers.py:334. Weight sharded on the output dim."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 gather_output: bool = True, fuse_matmul_bias: bool = False,
                 mp_group=None, name: Optional[str] = None,
                 mesh: Optional[ProcessMesh] = None, mp_axis: str = "mp"):
        super().__init__()
        self.gather_output = gather_output
        mesh = _resolve_mesh(mesh)
        self._mesh, self._mp_axis = mesh, mp_axis
        w = self.create_parameter([in_features, out_features],
                                  attr=weight_attr)
        self.weight = _shard_param(w, mesh, mp_axis, 1)
        if has_bias:
            b = self.create_parameter([out_features], is_bias=True)
            self.bias = _shard_param(b, mesh, mp_axis, 0)
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output and self._mesh is not None \
                and self._mp_axis in self._mesh.dim_names:
            # Replicate the out dim (reference: allgather of column shards).
            from ..api import reshard
            y = reshard(y, self._mesh,
                        [Replicate() for _ in range(self._mesh.ndim)])
        return y


class RowParallelLinear(nn.Layer):
    """Reference: mp_layers.py:541. Weight sharded on the input dim; the
    partial-sum reduction the reference emits as mp_allreduce is inserted by
    GSPMD's matmul partitioning."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None,
                 name: Optional[str] = None,
                 mesh: Optional[ProcessMesh] = None, mp_axis: str = "mp"):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        mesh = _resolve_mesh(mesh)
        self._mesh, self._mp_axis = mesh, mp_axis
        w = self.create_parameter([in_features, out_features],
                                  attr=weight_attr)
        self.weight = _shard_param(w, mesh, mp_axis, 0)
        # Bias applies after the reduction → replicated (reference keeps it
        # on rank0-equivalent; replication is the GSPMD analogue).
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(nn.Layer):
    """Reference: mp_layers.py:47. Embedding table sharded on the vocab dim;
    GSPMD partitions the gather (the reference's mask + allreduce)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name: Optional[str] = None,
                 mesh: Optional[ProcessMesh] = None, mp_axis: str = "mp"):
        super().__init__()
        self._mesh, self._mp_axis = _resolve_mesh(mesh), mp_axis
        w = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.Normal(std=0.02))
        self.weight = _shard_param(w, self._mesh, mp_axis, 0)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(nn.Layer):
    """Reference: mp_layers.py:742 (c_softmax_with_cross_entropy over the
    vocab-sharded logits). Here the ordinary fused softmax-CE runs on logits
    sharded over mp — XLA partitions the reductions (max/sumexp) with the
    same comm pattern the hand-written kernel uses."""

    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.softmax_with_cross_entropy(
            input, label, ignore_index=self.ignore_index)
