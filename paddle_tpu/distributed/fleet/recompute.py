"""Activation recompute (gradient checkpointing).

Reference capability: python/paddle/distributed/fleet/recompute/recompute.py:109
(``recompute(function, *args)`` — drop activations in forward, replay the
region in backward, with RNG-state restore). TPU-native redesign: the region
is captured as one *pure* function and wrapped in ``jax.checkpoint`` — XLA
then rematerializes it inside the compiled backward, which is strictly
better than the reference's eager replay (the recompute fuses into the
backward program, no Python re-execution, no RNG save/restore needed
because the pure function replays with identical PRNG usage by
construction).

Works in both execution modes:
- eager: the checkpointed pure fn is dispatched through the tape
  (tape._taped_call), so ``.backward()`` rematerializes the region;
  a Layer's parameters are lifted to explicit inputs so their grads flow.
- functional (inside jit / paddle_tpu.jit.to_static tracing): the wrapped
  call simply traces ``jax.checkpoint(fn)`` into the outer program.
"""
from __future__ import annotations

import contextlib

import jax

from ...core import state
from ...core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


@contextlib.contextmanager
def _swap_params(params, arrays):
    olds = [p._data for p in params]
    for p, a in zip(params, arrays):
        p._data = a
    try:
        yield
    finally:
        for p, o in zip(params, olds):
            p._data = o


def _collect_params(function):
    if hasattr(function, "parameters"):
        seen, out = set(), []
        for p in function.parameters():
            if isinstance(p, Tensor) and id(p) not in seen:
                seen.add(id(p))
                out.append(p)
        return out
    return []


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` now; rematerialize it during backward.

    ``function``: a Layer or callable over Tensors. Extra config kwargs
    accepted for API parity: ``preserve_rng_state`` (always effectively
    True — pure-function replay is deterministic) and ``use_reentrant``
    (ignored; there is only one implementation).
    """
    kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", True)

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensor_args = [args[i] for i in tensor_idx]
    params = _collect_params(function)
    all_inputs = tensor_args + params
    n_args = len(tensor_args)

    out_struct = {}

    def pure(*arrays):
        xs, ps = arrays[:n_args], arrays[n_args:]
        full = list(args)
        for i, x in zip(tensor_idx, xs):
            full[i] = Tensor(x, stop_gradient=args[i].stop_gradient)
        with _swap_params(params, ps), state.no_grad():
            out = function(*full, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        out_struct["multi"] = multi
        out_struct["is_tensor"] = [isinstance(o, Tensor) for o in outs]
        return tuple(o._data if isinstance(o, Tensor) else o for o in outs)

    ckpt = jax.checkpoint(pure)

    from ...autograd import tape
    outs = tape._taped_call("recompute", ckpt, all_inputs)
    # restore non-Tensor outputs to their original (raw array) type
    outs = [o if was_t else o._data
            for o, was_t in zip(outs, out_struct["is_tensor"])]
    if not out_struct["multi"]:
        return outs[0]
    return tuple(outs)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Reference: fleet.utils.recompute_sequential — chunk a Sequential and
    recompute each segment. ``ctx`` carries {"segments": N}."""
    segments = int((ctx or {}).get("segments", 1))
    layers = list(functions)
    if segments <= 1:
        chunks = [layers]
    else:
        size = max(1, len(layers) // segments)
        chunks = [layers[i:i + size] for i in range(0, len(layers), size)]

    out = args

    def run_chunk(chunk):
        def fn(*xs):
            y = xs
            for lyr in chunk:
                y = lyr(*y) if isinstance(y, tuple) else lyr(y)
                if not isinstance(y, tuple):
                    y = (y,)
            return y if len(y) > 1 else y[0]
        # lift every chunk layer's params
        class _Holder:
            def parameters(self):
                ps = []
                for lyr in chunk:
                    if hasattr(lyr, "parameters"):
                        ps.extend(lyr.parameters())
                return ps
            def __call__(self, *xs):
                return fn(*xs)
        return _Holder()

    for chunk in chunks:
        holder = run_chunk(chunk)
        out = recompute(holder, *(out if isinstance(out, tuple) else (out,)),
                        **kwargs)
    return out
