"""Host-driven pipeline-parallel runtime executing schedule action lists.

Reference capability: fleet/meta_parallel/pipeline_parallel.py —
``PipelineParallel.train_batch:697`` / ``forward_backward_pipeline:459``
(1F1B), ``PipelineParallelWithInterleave:1008`` (VPP), and the zero-bubble
scheduler pass (pipeline_zero_bubble.py:37). The reference implements each
schedule as a hand-written dygraph loop with NCCL p2p; the static path
instead compiles typed Job lists run by an executor.

TPU-native design — the static-path philosophy, host-driven:
- Every unit of work (stage forward, stage backward, input-grad-only,
  weight-grad-only) is ONE cached jitted program per stage-chunk. The
  schedule (pipeline_schedules.build_schedule) is data; this runtime is a
  small dependency-driven interpreter over it — the analogue of
  StandaloneExecutor running a Plan of micro-batch-tagged Jobs.
- "p2p" between stages is jax.device_put of the activation/cotangent to the
  next stage's device (XLA handles the transfer; on real multi-host TPU the
  same action lists drive per-stage programs whose boundaries are ICI
  transfers). Heterogeneous stages (embedding in / loss head out) are
  first-class: every stage-chunk has its own shapes and its own programs.
- Backward jobs REcompute the stage forward (jax.vjp inside the jitted
  backward) rather than stashing residuals across program boundaries —
  activation recompute at stage granularity, the reference's
  recompute_interval=1 discipline. Only the stage *input* is stashed, which
  is exactly what the 1F1B/ZB memory analysis counts.

The in-jit collective-permute GPipe pipeline (distributed/pipeline.py) is
the fully-compiled alternative for homogeneous stacks; this runtime is the
general schedule family over heterogeneous stages.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ...core import state
from ...core.tensor import Tensor
from ...nn.layer.base import Layer
from .pipeline_schedules import Action, build_schedule, validate_schedule
from .pp_layers import PipelineLayer
from ...core import enforce as E

__all__ = ["PipelineParallel"]


def _to_array(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class _StagePrograms:
    """Cached jitted programs for one stage-chunk (model part)."""

    def __init__(self, run_fns: Sequence[Callable],
                 named_params: List[Tuple[str, Any]],
                 is_first: bool, loss_fn: Optional[Callable]):
        self.named_params = named_params
        self.need_dx = not is_first
        self.loss_fn = loss_fn
        run = list(run_fns)

        def pure(param_arrays, x, label=None):
            saved = [(p, p._data) for _, p in named_params]
            try:
                for n, p in named_params:
                    p._data = param_arrays[n]
                with state.functional_mode():
                    t = Tensor(x)
                    for f in run:
                        t = f(t)
                    if loss_fn is not None:
                        t = loss_fn(t, Tensor(label))
                return t._data
            finally:
                for p, d in saved:
                    p._data = d

        self._pure = pure
        self.fwd = jax.jit(pure)

        has_loss = loss_fn is not None
        need_dx = self.need_dx

        def bwd(param_arrays, x, dy, label=None):
            if need_dx:
                f = (lambda pa, xx: pure(pa, xx, label)) if has_loss \
                    else (lambda pa, xx: pure(pa, xx))
                _, vjp = jax.vjp(f, param_arrays, x)
                dparams, dx = vjp(dy)
                return dparams, dx
            f = (lambda pa: pure(pa, x, label)) if has_loss \
                else (lambda pa: pure(pa, x))
            _, vjp = jax.vjp(f, param_arrays)
            (dparams,) = vjp(dy)
            return dparams, None

        self.bwd = jax.jit(bwd)

        # zero-bubble split: input-grad job (critical path) and
        # weight-grad job (slides into the bubble)
        def bwd_input(param_arrays, x, dy, label=None):
            f = (lambda xx: pure(param_arrays, xx, label)) if has_loss \
                else (lambda xx: pure(param_arrays, xx))
            _, vjp = jax.vjp(f, x)
            (dx,) = vjp(dy)
            return dx

        def bwd_weight(param_arrays, x, dy, label=None):
            f = (lambda pa: pure(pa, x, label)) if has_loss \
                else (lambda pa: pure(pa, x))
            _, vjp = jax.vjp(f, param_arrays)
            (dparams,) = vjp(dy)
            return dparams

        self.bwd_input = jax.jit(bwd_input) if need_dx else None
        self.bwd_weight = jax.jit(bwd_weight)


class PipelineParallel:
    """Schedule-driven pipeline trainer over a PipelineLayer.

    ``layer`` must be segmented into ``num_stages * num_chunks`` parts
    (build it with ``num_stages=num_stages * num_chunks``); part ``p`` is
    chunk ``p // num_stages`` on stage ``p % num_stages`` (reference VPP
    assignment). ``schedule`` ∈ {FThenB, 1F1B, 1F1B-Interleave, ZBH1}.

    ``devices='auto'`` places stage ``s``'s parameters on
    ``jax.devices()[s % n]`` and moves activations between stage devices
    (the single-host stand-in for per-stage TPU slices).
    """

    def __init__(self, layer: PipelineLayer, num_micro: int,
                 schedule: str = "1F1B", num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None,
                 devices: Optional[str] = None):
        parts = layer.get_num_stages()
        self.layer = layer
        self.num_stages = num_stages or parts
        if parts % self.num_stages != 0:
            raise E.InvalidArgumentError(
                f"layer has {parts} parts, not divisible by "
                f"{self.num_stages} stages")
        self.num_chunks = parts // self.num_stages
        self.num_micro = num_micro
        self.schedule_name = schedule
        self.loss_fn = loss_fn or layer.loss_fn
        if self.loss_fn is None:
            raise E.InvalidArgumentError("pipeline training requires a loss_fn")
        self.sched = build_schedule(schedule, self.num_stages, num_micro,
                                    self.num_chunks)
        validate_schedule(self.sched, num_micro, self.num_chunks)

        self._devices = None
        if devices == "auto":
            devs = jax.devices()
            self._devices = [devs[s % len(devs)]
                             for s in range(self.num_stages)]

        # Build per-part programs. Part p == position p in pipeline order.
        self._programs: List[_StagePrograms] = []
        slices = layer.stage_slices()
        for p in range(parts):
            lo, hi = slices[p]
            named: List[Tuple[str, Any]] = []
            for i in range(lo, hi):
                sub = layer._sub_layers.get(str(i))
                if sub is not None:
                    named.extend((f"{i}.{n}", par)
                                 for n, par in sub.named_parameters()
                                 if par is not None)
            if self._devices is not None:
                dev = self._devices[p % self.num_stages]
                for _, par in named:
                    par._data = jax.device_put(par._data, dev)
            self._programs.append(_StagePrograms(
                layer.get_stage_layers(p), named,
                is_first=(p == 0),
                loss_fn=self.loss_fn if p == parts - 1 else None))

    # -- helpers ------------------------------------------------------------
    def _position(self, stage: int, chunk: int) -> int:
        return chunk * self.num_stages + stage

    def _stage_dev(self, pos: int):
        if self._devices is None:
            return None
        return self._devices[pos % self.num_stages]

    def _put(self, arr, pos: int):
        dev = self._stage_dev(pos)
        return arr if dev is None else jax.device_put(arr, dev)

    # -- the interpreter ----------------------------------------------------
    def forward_backward_pipeline(self, data, labels,
                                  scale: float = 1.0):
        """Run one batch through the schedule, accumulating parameter grads
        into ``Parameter.grad``. Returns the mean micro-loss as a Tensor.

        ``scale`` multiplies the loss cotangent (GradScaler loss scaling).
        """
        M, S = self.num_micro, self.num_stages
        P_total = len(self._programs)
        data = _to_array(data)
        labels = _to_array(labels)
        if data.shape[0] % M != 0:
            raise E.InvalidArgumentError(
                f"batch {data.shape[0]} not divisible by {M} micro-batches")
        micro_x = data.reshape(M, data.shape[0] // M, *data.shape[1:])
        micro_y = labels.reshape(M, labels.shape[0] // M, *labels.shape[1:])

        y_out: Dict[Tuple[int, int], Any] = {}
        x_in: Dict[Tuple[int, int], Any] = {}
        dy: Dict[Tuple[int, int], Any] = {}
        pend_w: Dict[Tuple[int, int], Tuple[Any, Any]] = {}
        losses: Dict[int, Any] = {}
        grad_acc: List[Dict[str, Any]] = [dict() for _ in range(P_total)]
        cot = jnp.asarray(scale / M, jnp.float32)

        def accumulate(p, dparams):
            acc = grad_acc[p]
            for n, g in dparams.items():
                acc[n] = g if n not in acc else acc[n] + g

        def ready(stage: int, a: Action) -> bool:
            p = self._position(stage, a.chunk)
            if a.kind == "F":
                return p == 0 or (p - 1, a.micro) in y_out
            if a.kind in ("B", "BI"):
                if p == P_total - 1:
                    return a.micro in losses
                return (p, a.micro) in dy
            return (p, a.micro) in pend_w          # BW

        def execute(stage: int, a: Action) -> None:
            p = self._position(stage, a.chunk)
            prog = self._programs[p]
            params = {n: par._data for n, par in prog.named_params}
            last = p == P_total - 1
            if a.kind == "F":
                x = micro_x[a.micro] if p == 0 \
                    else self._put(y_out.pop((p - 1, a.micro)), p)
                x_in[(p, a.micro)] = x
                if last:
                    losses[a.micro] = prog.fwd(params, x, micro_y[a.micro])
                else:
                    y_out[(p, a.micro)] = prog.fwd(params, x)
                return
            x = x_in[(p, a.micro)] if a.kind != "BW" else None
            if a.kind in ("B", "BI"):
                d = (cot.astype(losses[a.micro].dtype) if last
                     else self._put(dy.pop((p, a.micro)), p))
            if a.kind == "B":
                if last:
                    dparams, dx = prog.bwd(params, x, d, micro_y[a.micro])
                else:
                    dparams, dx = prog.bwd(params, x, d)
                accumulate(p, dparams)
                if dx is not None and p > 0:
                    dy[(p - 1, a.micro)] = dx
                del x_in[(p, a.micro)]
            elif a.kind == "BI":
                if prog.bwd_input is not None:
                    dx = (prog.bwd_input(params, x, d, micro_y[a.micro])
                          if last else prog.bwd_input(params, x, d))
                    if p > 0:
                        dy[(p - 1, a.micro)] = dx
                pend_w[(p, a.micro)] = (x, d)
            else:                                   # BW
                xs, d = pend_w.pop((p, a.micro))
                dparams = (prog.bwd_weight(params, xs, d, micro_y[a.micro])
                           if last else prog.bwd_weight(params, xs, d))
                accumulate(p, dparams)
                del x_in[(p, a.micro)]

        ptr = [0] * S
        done, total = 0, sum(len(s) for s in self.sched)
        while done < total:
            progressed = False
            for s in range(S):
                while ptr[s] < len(self.sched[s]) and \
                        ready(s, self.sched[s][ptr[s]]):
                    execute(s, self.sched[s][ptr[s]])
                    ptr[s] += 1
                    done += 1
                    progressed = True
            if not progressed:
                stuck = {s: self.sched[s][ptr[s]] for s in range(S)
                         if ptr[s] < len(self.sched[s])}
                raise E.PreconditionNotMetError(
                    f"pipeline schedule deadlock; waiting on {stuck}")

        # write accumulated grads onto Parameters (shared params get
        # contributions from every owning part — reference shared-weight
        # allreduce semantics)
        for p in range(P_total):
            for n, par in self._programs[p].named_params:
                g = grad_acc[p].get(n)
                if g is None:
                    continue
                if par.grad is None:
                    par.grad = Tensor(g)
                else:
                    par.grad._data = par.grad._data + g
        mean_loss = sum(jax.device_get(losses[m]) for m in range(M)) / M
        return Tensor(jnp.asarray(mean_loss))

    def train_batch(self, data, labels, optimizer=None, scaler=None):
        """Reference surface: PipelineParallel.train_batch(data, opt) —
        forward+backward over the schedule, then one optimizer step."""
        scale = float(scaler.get_loss_scaling()) \
            if scaler is not None and scaler.is_enable() else 1.0
        loss = self.forward_backward_pipeline(data, labels, scale=scale)
        if optimizer is not None:
            if scaler is not None and scaler.is_enable():
                scaler.step(optimizer)
                scaler.update()
            else:
                optimizer.step()
            optimizer.clear_grad()
        return loss

    def eval_batch(self, data, labels):
        """Forward-only pipeline (no grads)."""
        M = self.num_micro
        data = _to_array(data)
        labels = _to_array(labels)
        micro_x = data.reshape(M, data.shape[0] // M, *data.shape[1:])
        micro_y = labels.reshape(M, labels.shape[0] // M, *labels.shape[1:])
        P_total = len(self._programs)
        losses = []
        for m in range(M):
            x = micro_x[m]
            for p in range(P_total):
                prog = self._programs[p]
                params = {n: par._data for n, par in prog.named_params}
                x = self._put(x, p)
                if p == P_total - 1:
                    losses.append(prog.fwd(params, x, micro_y[m]))
                else:
                    x = prog.fwd(params, x)
        return Tensor(jnp.mean(jnp.stack(losses)))
