"""paddle.distributed.fleet parity surface (hybrid-parallel programming).

Reference: python/paddle/distributed/fleet/__init__.py.
"""
from . import fleet as _fleet_mod
from .fleet import (DistributedStrategy, Fleet, distributed_model,  # noqa
                    distributed_optimizer, get_hybrid_communicate_group,
                    init, is_first_worker, worker_index, worker_num)
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa
                        RowParallelLinear, VocabParallelEmbedding)
from .pp_layers import (LayerDesc, PipelineLayer, SegmentLayers,  # noqa
                        SharedLayerDesc)
from . import elastic  # noqa
from .elastic import ElasticManager, run_elastic  # noqa
from . import pipeline_schedules  # noqa
from .pipeline_runtime import PipelineParallel  # noqa
from .recompute import recompute, recompute_sequential  # noqa
from . import sequence_parallel_utils  # noqa
from . import utils  # noqa
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa

# meta_parallel namespace parity (reference: fleet/meta_parallel/__init__.py
# exports the mpu layers too).
from . import mp_layers as meta_parallel  # noqa
