"""paddle.distributed.fleet parity surface (hybrid-parallel programming).

Reference: python/paddle/distributed/fleet/__init__.py.
"""
from . import fleet as _fleet_mod
from .fleet import (DistributedStrategy, Fleet, distributed_model,  # noqa
                    distributed_optimizer, get_hybrid_communicate_group,
                    init, is_first_worker, worker_index, worker_num)
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa
                        RowParallelLinear, VocabParallelEmbedding)
from .pp_layers import (LayerDesc, PipelineLayer, SegmentLayers,  # noqa
                        SharedLayerDesc)
from . import elastic  # noqa
from .elastic import ElasticManager, run_elastic  # noqa
from . import pipeline_schedules  # noqa
from .pipeline_runtime import PipelineParallel  # noqa
from .recompute import recompute, recompute_sequential  # noqa
from . import sequence_parallel_utils  # noqa
from . import utils  # noqa
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa

# meta_parallel namespace parity (reference: fleet/meta_parallel/__init__.py
# exports the mpu layers too).
from . import mp_layers as meta_parallel  # noqa
from ...core import enforce as E


# -- PS-era role makers / data generators (reference: fleet/base/
# role_maker.py, fleet/data_generator) — parameter-server machinery,
# recorded as out of scope (docs/CAPABILITY_DELTA.md); Role/UtilBase are
# kept live because collective mode uses them too.

class Role:
    """reference: role_maker.py Role constants."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class UtilBase:
    """reference: fleet/base/util_factory.py UtilBase — cross-worker
    helpers. Multi-process: host values ride the KV-store object
    collectives; single-process they are local identities."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        from ..env import get_world_size

        if get_world_size() <= 1:
            return input
        gathered = self.all_gather(input, comm_world)
        if mode == "sum":
            out = gathered[0]
            for g in gathered[1:]:
                out = out + g
            return out
        if mode == "max":
            return max(gathered)
        if mode == "min":
            return min(gathered)
        raise E.InvalidArgumentError(f"all_reduce: unknown mode {mode!r}")

    def barrier(self, comm_world="worker"):
        from ..collective import barrier as _barrier

        _barrier()

    def all_gather(self, input, comm_world="worker"):
        from ..collective import all_gather_object
        from ..env import get_world_size

        if get_world_size() <= 1:
            return [input]
        out = []
        all_gather_object(out, input)
        return out

    def get_file_shard(self, files):
        from ..env import get_rank, get_world_size

        n = get_world_size()
        r = get_rank()
        return files[r::n]


class PaddleCloudRoleMaker:
    """Collective role maker (reference: role_maker.py
    PaddleCloudRoleMaker): answers rank/size questions from the
    jax.distributed environment; PS mode raises."""

    def __init__(self, is_collective=True, **kwargs):
        if not is_collective:
            raise NotImplementedError(
                "parameter-server role negotiation is out of scope "
                "(docs/CAPABILITY_DELTA.md); use is_collective=True")
        self._util = UtilBase()

    def _worker_index(self):
        from ..env import get_rank

        return get_rank()

    def _worker_num(self):
        from ..env import get_world_size

        return get_world_size()

    def _is_worker(self):
        return True

    def _role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective)
        self._kwargs = kwargs


def _ps_gate(name):
    class _Gated:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{name} feeds the parameter-server dataset pipeline, "
                "out of scope on this runtime "
                "(docs/CAPABILITY_DELTA.md)")
    _Gated.__name__ = name
    return _Gated


MultiSlotDataGenerator = _ps_gate("MultiSlotDataGenerator")
MultiSlotStringDataGenerator = _ps_gate("MultiSlotStringDataGenerator")
