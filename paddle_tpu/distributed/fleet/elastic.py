"""Elastic training: fault detection + automatic job restart.

Reference capability: python/paddle/distributed/fleet/elastic/manager.py:124
(ElasticManager — watches workers via etcd heartbeats, relaunches the job
on failure up to a restart budget, scale-in/out between bounds).
TPU-native redesign: there is no etcd — fault detection IS the launch
controller's fail-fast watcher (launch/main.py), and elasticity is a
restart policy wrapped around it. Scale-in support: on each restart the
manager can shrink to the largest viable worker count within
[min_nproc, nproc] (the reference's np=min:max band).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

__all__ = ["ElasticManager", "ElasticStatus", "run_elastic"]


class ElasticStatus:
    """reference: elastic/manager.py ElasticStatus enum."""
    COMPLETED = "completed"
    RESTART = "restart"
    ERROR = "error"
    EXIT = "exit"


class ElasticManager:
    """Restart policy around the launch controller (reference:
    ElasticManager.run/watch loop)."""

    def __init__(self, max_restarts: int = 3, min_nproc: Optional[int] = None,
                 restart_delay: float = 1.0,
                 launcher: Optional[Callable] = None):
        self.max_restarts = int(max_restarts)
        self.min_nproc = min_nproc
        self.restart_delay = restart_delay
        if launcher is None:
            from ..launch.main import launch as launcher
        self._launch = launcher
        self.restarts = 0
        self.events = []   # (timestamp, status, detail)

    def _record(self, status, detail):
        self.events.append((time.time(), status, detail))

    def run(self, script: str, script_args: Sequence[str] = (),
            nproc_per_node: int = 1, **launch_kwargs) -> int:
        """Run the job; on worker failure relaunch (same size, then
        scale-in toward min_nproc when repeated failures suggest a sick
        worker). Returns the final exit code (0 = completed). The restart
        budget is per-job: each run() starts fresh."""
        self.restarts = 0
        self.events = []
        nproc = nproc_per_node
        while True:
            rc = self._launch(script, script_args,
                              nproc_per_node=nproc, **launch_kwargs)
            if rc == 0:
                self._record(ElasticStatus.COMPLETED, {"nproc": nproc})
                return 0
            if self.restarts >= self.max_restarts:
                self._record(ElasticStatus.ERROR,
                             {"nproc": nproc, "rc": rc,
                              "reason": "restart budget exhausted"})
                return rc
            self.restarts += 1
            # scale-in after half the budget is burned (reference scale-in
            # when a peer stays unhealthy)
            if (self.min_nproc is not None and nproc > self.min_nproc
                    and self.restarts > self.max_restarts // 2):
                nproc = max(self.min_nproc, nproc - 1)
            self._record(ElasticStatus.RESTART,
                         {"nproc": nproc, "rc": rc,
                          "attempt": self.restarts})
            time.sleep(self.restart_delay)


def run_elastic(script: str, script_args: Sequence[str] = (),
                nproc_per_node: int = 1, max_restarts: int = 3,
                min_nproc: Optional[int] = None, **launch_kwargs) -> int:
    """Functional form (reference: the `--elastic_level` launch path)."""
    return ElasticManager(max_restarts=max_restarts,
                          min_nproc=min_nproc).run(
        script, script_args, nproc_per_node=nproc_per_node,
        **launch_kwargs)
