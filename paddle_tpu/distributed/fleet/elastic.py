"""Elastic training: fault detection + automatic job restart.

Reference capability: python/paddle/distributed/fleet/elastic/manager.py:124
(ElasticManager — watches workers via etcd heartbeats, relaunches the job
on failure up to a restart budget, scale-in/out between bounds).
TPU-native redesign: there is no etcd — fault detection IS the launch
controller's fail-fast watcher (launch/main.py), and elasticity is a
restart policy wrapped around it. Scale-in support: on each restart the
manager can shrink to the largest viable worker count within
[min_nproc, nproc] (the reference's np=min:max band).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

__all__ = ["ElasticManager", "ElasticStatus", "run_elastic"]


class ElasticStatus:
    """reference: elastic/manager.py ElasticStatus enum."""
    COMPLETED = "completed"
    RESTART = "restart"
    ERROR = "error"
    EXIT = "exit"


class ElasticManager:
    """Restart policy around the launch controller (reference:
    ElasticManager.run/watch loop)."""

    def __init__(self, max_restarts: int = 3, min_nproc: Optional[int] = None,
                 restart_delay: float = 1.0,
                 launcher: Optional[Callable] = None):
        self.max_restarts = int(max_restarts)
        self.min_nproc = min_nproc
        self.restart_delay = restart_delay
        if launcher is None:
            from ..launch.main import launch as launcher
        self._launch = launcher
        self.restarts = 0
        self.events = []   # (timestamp, status, detail)

    def _record(self, status, detail):
        self.events.append((time.time(), status, detail))

    def run(self, script: str, script_args: Sequence[str] = (),
            nproc_per_node: int = 1, **launch_kwargs) -> int:
        """Run the job; on worker failure relaunch (same size, then
        scale-in toward min_nproc when repeated failures suggest a sick
        worker). Returns the final exit code (0 = completed). The restart
        budget is per-job: each run() starts fresh."""
        self.restarts = 0
        self.events = []
        nproc = nproc_per_node
        base_env = dict(launch_kwargs.pop("extra_env", None) or {})
        run_idx = 0
        while True:
            # export the world incarnation (like run_adaptive): the
            # death/abort markers are generation-keyed, so each
            # relaunch must advance the generation or a marker from the
            # previous incarnation (same shared heartbeat dir) would
            # instantly kill the new world
            env = dict(base_env, PADDLE_ELASTIC_RUN=str(run_idx))
            run_idx += 1
            rc = self._launch(script, script_args,
                              nproc_per_node=nproc, extra_env=env,
                              **launch_kwargs)
            if rc == 0:
                self._record(ElasticStatus.COMPLETED, {"nproc": nproc})
                return 0
            if self.restarts >= self.max_restarts:
                self._record(ElasticStatus.ERROR,
                             {"nproc": nproc, "rc": rc,
                              "reason": "restart budget exhausted"})
                return rc
            self.restarts += 1
            # Typed coordinated abort (collective.coordinated_abort):
            # PEER_FAILURE_RC means an INNOCENT rank exited on a typed
            # CollectiveTimeout/PeerLostError because a PEER died —
            # restart the world, but never feed the scale-in heuristic
            # off the innocent rank's rc (the exiting worker is not the
            # sick one).
            peer_failure = rc == PEER_FAILURE_RC
            # scale-in after half the budget is burned (reference scale-in
            # when a peer stays unhealthy)
            if (not peer_failure and self.min_nproc is not None
                    and nproc > self.min_nproc
                    and self.restarts > self.max_restarts // 2):
                nproc = max(self.min_nproc, nproc - 1)
            self._record(ElasticStatus.RESTART,
                         {"nproc": nproc, "rc": rc,
                          "attempt": self.restarts,
                          "reason": "peer-failure" if peer_failure
                          else "worker-failure"})
            time.sleep(self.restart_delay)


def run_elastic(script: str, script_args: Sequence[str] = (),
                nproc_per_node: int = 1, max_restarts: int = 3,
                min_nproc: Optional[int] = None, **launch_kwargs) -> int:
    """Functional form (reference: the `--elastic_level` launch path)."""
    return ElasticManager(max_restarts=max_restarts,
                          min_nproc=min_nproc).run(
        script, script_args, nproc_per_node=nproc_per_node,
        **launch_kwargs)


# -- scale-out / re-admission (reference: ElasticManager watching etcd
# -- membership, fleet/elastic/manager.py:124: the np=min:max band plus
# -- _match()-triggered world rebuilds) --------------------------------------

from ..launch.main import PEER_FAILURE_RC, RESCALE_RC  # one home for the
#                                                       # protocol rcs


class _BoundedSignals:
    """Control-loop isolation for ``run_serving``'s legacy ``signals``
    callable: each call runs on a daemon worker thread joined for at
    most ``timeout`` seconds. A call that blows the bound returns None
    (no payload — never fabricated) and marks the replica WEDGED:
    while its call is still outstanding, later ticks skip it instantly
    instead of stacking threads, so one frozen replica delays the
    whole fleet's tick by at most one bound, once. A late result from
    a recovered callable is kept and served on the next ask.
    ``timeout`` None/<=0 = pass-through (the pre-federation blocking
    semantics). Exceptions surface to the caller's existing
    try/except as None results."""

    def __init__(self, fn, timeout: Optional[float]):
        self._fn = fn
        self._timeout = timeout
        self._pending: dict = {}     # name -> (result box, done event)
        self._workers: dict = {}     # name -> (thread, request queue)

    def __call__(self, name, handle):
        if not self._timeout or self._timeout <= 0:
            return self._fn(name, handle)
        import queue as _queue
        import threading

        pend = self._pending.get(name)
        if pend is not None:
            box, done = pend
            if not done.is_set():
                return None          # still wedged: skip instantly
            self._pending.pop(name, None)
            return box.get("value")  # late result from a recovery
        w = self._workers.get(name)
        if w is None or not w[0].is_alive():
            # ONE persistent worker per name, created lazily and fed
            # through a queue — not a thread per call: the healthy
            # common case (every replica, every 50ms tick) must not
            # pay thread create/join churn to buy wedge protection
            # for the rare frozen callable
            req: _queue.Queue = _queue.Queue()

            def loop():
                while True:
                    item = req.get()
                    if item is None:
                        return       # retired
                    h, box_, done_ = item
                    try:
                        box_["value"] = self._fn(name, h)
                    except Exception:
                        box_["value"] = None
                    done_.set()

            th = threading.Thread(target=loop, daemon=True,
                                  name=f"signals:{name}")
            th.start()
            w = (th, req)
            self._workers[name] = w
        box: dict = {}
        done = threading.Event()
        w[1].put((handle, box, done))
        if done.wait(self._timeout):
            return box.get("value")
        self._pending[name] = (box, done)
        return None

    def discard_pending(self, name):
        """Drop an outstanding call's future result (the drain
        barrier: a payload captured before ``begin_drain`` must not
        be served inside the drain wait). The worker keeps running —
        a wedged call finishes into a box nobody reads."""
        self._pending.pop(name, None)

    def retire(self, name):
        """The name will never be asked again (its replica stopped or
        was replaced; numbering is monotonic): drop the pending box
        (it would pin the stopped replica's handle) and shut the
        worker down — the sentinel lets a wedged call finish into a
        box nobody reads, then the thread exits instead of idling for
        the rest of the run."""
        self._pending.pop(name, None)
        w = self._workers.pop(name, None)
        if w is not None:
            w[1].put(None)


class AdaptiveElasticManager(ElasticManager):
    """Elastic training with scale-IN on failure and scale-OUT on worker
    re-admission, resuming each world from the latest checkpoint.

    The reference watches etcd membership: when a node's lease lapses the
    world restarts smaller; when a (re)joined node registers, the world
    restarts at the larger size (manager.py:124 `_match` + relaunch).
    TPU-native transport: no etcd — a DOWN worker is whatever the launch
    watcher reported (crash rc or heartbeat rc=124), and re-admission is
    an announcement file in ``membership_dir`` (``worker*.up``, touched
    by the recovered host's agent) or an automatic ``readmit_after``
    backoff expiry. A membership GROWTH during a running world triggers a
    controlled stop (launch control_dir rescale flag, rc=125) and a
    relaunch at the larger size; workers resume from the latest
    checkpoint (distributed.checkpoint reshards on load, so 3→2→3-style
    world changes re-partition state automatically)."""

    def __init__(self, max_restarts: int = 10,
                 min_nproc: Optional[int] = None,
                 restart_delay: float = 0.2,
                 readmit_after: Optional[float] = None,
                 launcher: Optional[Callable] = None):
        super().__init__(max_restarts=max_restarts, min_nproc=min_nproc,
                         restart_delay=restart_delay, launcher=launcher)
        self.readmit_after = readmit_after
        self._down_times: list = []      # one entry per currently-down slot
        self._up_consumed: set = set()   # consumed worker*.up file paths

    # membership -------------------------------------------------------------
    def _capacity(self, nproc_target: int, membership_dir) -> int:
        """Current admissible world size: target minus still-down slots.
        A down slot is re-admitted by an unconsumed ``worker*.up``
        announcement or by ``readmit_after`` expiry."""
        import glob
        import os

        if membership_dir:
            # consumed announcements tracked by FILENAME, not count: a
            # consumed up-file being deleted later must not swallow a
            # different worker's future announcement
            ups = sorted(glob.glob(os.path.join(membership_dir,
                                                "worker*.up")))
            for u in ups:
                if u in self._up_consumed or not self._down_times:
                    continue
                self._down_times.pop(0)
                self._up_consumed.add(u)
        if self.readmit_after is not None:
            now = time.time()
            self._down_times = [t for t in self._down_times
                                if now - t < self.readmit_after]
        return max(1, nproc_target - len(self._down_times))

    def run_adaptive(self, script: str, script_args: Sequence[str] = (),
                     nproc_per_node: int = 1,
                     membership_dir: Optional[str] = None,
                     ckpt_dir: Optional[str] = None,
                     poll_interval: float = 0.5,
                     **launch_kwargs) -> int:
        """Run the job with world-size adaptation. Returns 0 when a world
        completes, else the last failure rc once the restart budget is
        exhausted. ``ckpt_dir`` is exported as PADDLE_ELASTIC_CKPT_DIR
        for the load_state/save_state worker helpers."""
        import os
        import tempfile
        import threading

        self.restarts = 0
        self.events = []
        self._down_times = []
        # baseline pre-existing announcements: an up-file left over from
        # a previous job must not instantly re-admit this job's first
        # crash
        self._up_consumed = set()
        if membership_dir:
            import glob
            self._up_consumed = set(glob.glob(
                os.path.join(membership_dir, "worker*.up")))
        ctl = tempfile.mkdtemp(prefix="paddle_elastic_ctl_")
        extra_env = dict(launch_kwargs.pop("extra_env", None) or {})
        if ckpt_dir:
            os.makedirs(ckpt_dir, exist_ok=True)
            extra_env["PADDLE_ELASTIC_CKPT_DIR"] = ckpt_dir
        import shutil
        try:
            run_idx = 0
            rc = 0
            while True:
                np_now = self._capacity(nproc_per_node, membership_dir)
                if self.min_nproc is not None and np_now < self.min_nproc:
                    self._record(ElasticStatus.ERROR,
                                 {"reason": "below min_nproc",
                                  "capacity": np_now})
                    return rc or 1
                flag = os.path.join(ctl, "rescale")
                if os.path.exists(flag):
                    os.remove(flag)
                stop_watch = threading.Event()

                def watch_membership(np_running=np_now):
                    while not stop_watch.is_set():
                        if self._capacity(nproc_per_node,
                                          membership_dir) > np_running:
                            try:
                                with open(flag, "w"):
                                    pass
                                return
                            except OSError as e:
                                # the re-admission was already consumed by
                                # _capacity — keep retrying the flag write,
                                # or the scale-out is silently lost
                                import sys
                                print(f"[elastic] rescale flag write failed "
                                      f"({e}); retrying", file=sys.stderr)
                        stop_watch.wait(poll_interval)

                watcher = None
                if np_now < nproc_per_node and (membership_dir
                                                or self.readmit_after):
                    watcher = threading.Thread(target=watch_membership,
                                               daemon=True)
                    watcher.start()
                env = dict(extra_env, PADDLE_ELASTIC_RUN=str(run_idx))
                kw = dict(launch_kwargs)
                if kw.get("log_dir"):
                    # one dir per world incarnation — a relaunch must not
                    # overwrite the previous world's workerlogs
                    kw["log_dir"] = os.path.join(kw["log_dir"],
                                                 f"run{run_idx}")
                try:
                    rc = self._launch(script, script_args,
                                      nproc_per_node=np_now,
                                      extra_env=env, control_dir=ctl,
                                      **kw)
                finally:
                    stop_watch.set()
                    if watcher:
                        watcher.join(timeout=5)
                run_idx += 1
                if rc == 0:
                    self._record(ElasticStatus.COMPLETED, {"nproc": np_now})
                    return 0
                if rc == RESCALE_RC and os.path.exists(flag):
                    # controlled stop for scale-out (confirmed by OUR flag —
                    # a worker exiting 125 on its own is a failure, not a
                    # rescale): no budget burn
                    self._record(ElasticStatus.RESTART,
                                 {"nproc": np_now, "reason": "scale-out"})
                    continue
                if self.restarts >= self.max_restarts:
                    self._record(ElasticStatus.ERROR,
                                 {"nproc": np_now, "rc": rc,
                                  "reason": "restart budget exhausted"})
                    return rc
                self.restarts += 1
                if rc != PEER_FAILURE_RC:
                    self._down_times.append(time.time())
                else:
                    # coordinated abort: the FIRST observed exit was an
                    # INNOCENT rank's typed collective fault — marking
                    # a slot down off its rc would permanently shrink
                    # the next world (no up-file will ever re-admit a
                    # worker that was never sick); restart full-size
                    pass
                self._record(ElasticStatus.RESTART,
                             {"nproc": np_now, "rc": rc,
                              "attempt": self.restarts,
                              "reason": "peer-failure"
                              if rc == PEER_FAILURE_RC
                              else "worker-failure"})
                time.sleep(self.restart_delay)
        finally:
            # the control tempdir (rescale flag) must not leak
            # across run_adaptive calls
            shutil.rmtree(ctl, ignore_errors=True)


    # -- serving-replica elasticity (ROADMAP item 5, acting half) ------------
    #
    # Training elasticity above re-forms a WORLD between launches; serving
    # elasticity manages a fleet of independent engine REPLICAS against the
    # autoscale demand signals the SLO plane computes
    # (monitor/slo.demand_model — the same payload /slo serves as
    # serving.autoscale.*). Transport-agnostic: the caller provides
    # spawn/stop/signals callables (a k8s deployment, subprocesses, or
    # in-process engines in tests); the controller owns the POLICY — scale
    # toward the demand hint within [min, max], drain before stopping, and
    # replace heartbeat-stale replicas.

    def _drain_and_stop(self, name, handle, *, signals, drain, stop,
                        drain_timeout: float, poll_interval: float,
                        state_fn=None, ckpt_dir=None,
                        checkpoint: bool = True,
                        discard_stale_signals: bool = True,
                        stop_event=None, view=None) -> bool:
        """The scale-in path, in the order that keeps it crash-safe:
        (1) checkpoint via the PR 2 CheckpointManager (atomic commit —
        a kill -9 anywhere after this leaves only committed state;
        ``checkpoint=False`` on a RETRY of the same victim, so a
        repeatedly-timing-out drain does not re-save identical state
        every tick), (2) tell the replica to stop admitting
        (``drain``, default ``handle.begin_drain()``, idempotent: new
        submissions shed with retry hints), (3) WAIT until its signals
        report ``drain_safe`` (no queued, no resident requests — live
        work finishes, never dropped), (4) stop it. Returns False on
        drain timeout — or when ``stop_event`` fires, so a controller
        shutdown never hangs behind a long decode — WITHOUT stopping:
        a replica is stopped only when ``drain_safe``; the caller
        retries on a later tick."""
        import os

        from ...testing import faults as _faults

        root = ckpt_dir or os.environ.get("PADDLE_ELASTIC_CKPT_DIR")
        if checkpoint and state_fn is not None and root:
            _faults.hit("drain.checkpoint")
            mgr = _manager_for(root)
            step = (mgr.latest_step() or 0) + 1
            mgr.save(step, dict(state_fn()), blocking=True)
        drain(name, handle)
        if discard_stale_signals and hasattr(signals,
                                             "discard_pending"):
            # a signals() call that wedged BEFORE the drain could
            # complete late with a pre-drain "idle" payload — its
            # drain_safe must never authorize this stop. ONCE, when
            # the drain first commits (the checkpoint=False retry
            # discipline): re-discarding on every retry tick would
            # re-spawn a bounded worker per tick for a wedged
            # callable and re-block the loop by the full bound each
            # time — the exact stall _BoundedSignals exists to
            # prevent.
            signals.discard_pending(name)
        deadline = time.monotonic() + drain_timeout
        while True:
            sig = None
            if view is not None:
                # federation first: a fresh frame's autoscale payload
                # answers drain_safe without touching the (possibly
                # wedged, possibly remote) signals callable. Only a
                # frame that already REFLECTS the drain counts — a
                # pre-drain frame still inside the staleness window
                # reports the idle state from before admission and
                # must not authorize the stop (begin_drain
                # force-publishes, so the draining frame arrives as
                # fast as the transport can carry it).
                view.poll([name])
                frame = view.fresh_frames([name]).get(name)
                if frame is not None and frame.get("draining"):
                    sig = frame.get("autoscale")
                    if not isinstance(sig, dict):
                        sig = None   # remote input: fall through
            if sig is None:
                try:
                    sig = signals(name, handle)
                except Exception:
                    sig = None
            if sig and sig.get("drain_safe"):
                break
            if time.monotonic() >= deadline:
                return False
            if stop_event is not None and stop_event.is_set():
                return False
            time.sleep(poll_interval)
        _faults.hit("drain.stop")
        stop(name, handle)
        return True

    def run_serving(self, spawn, stop, *, signals=None, drain=None,
                    min_replicas: int = 1, max_replicas: int = 4,
                    poll_interval: float = 0.05,
                    drain_timeout: float = 60.0,
                    heartbeat_dir: Optional[str] = None,
                    heartbeat_timeout: float = 0.0,
                    state_fn=None, ckpt_dir: Optional[str] = None,
                    max_ticks: Optional[int] = None,
                    stop_event=None, federation=None,
                    fleet_burn_scaling: Optional[bool] = None,
                    failover: Optional[bool] = None,
                    signal_timeout: Optional[float] = 5.0,
                    on_tick=None) -> dict:
        """Drive a serving-replica fleet against the autoscale signals.

        ``spawn(name) -> handle`` creates a replica; ``stop(name,
        handle)`` terminates one; ``signals(name, handle) -> dict``
        returns its demand payload (default:
        ``handle.autoscale_payload()`` — the engine's own
        ``monitor/slo.demand_model`` view); ``drain(name, handle)``
        begins its drain (default ``handle.begin_drain()``).

        Each tick: (1) heartbeat-stale replicas (``heartbeat_dir`` +
        ``heartbeat_timeout``, via ``heartbeat.stale_names``) are
        force-stopped and replaced — a wedged replica cannot execute a
        drain protocol, so it burns a unit of the restart budget
        instead; (2) fleet demand = sum of per-replica
        ``demand_estimate``, and the fleet scales toward
        ``ceil(demand)`` clamped to [min_replicas, max_replicas] —
        scale-out spawns immediately, scale-in retires the NEWEST
        replica (oldest keep their warm compile caches) through
        :meth:`_drain_and_stop`, at most one per tick, and ONLY once
        its ``drain_safe`` signal holds. A drain is COMMITTED: once
        ``begin_drain`` ran, the replica sheds all new work (the
        engine has no un-drain), so it stops counting toward
        effective capacity — a demand rise mid-drain spawns a
        replacement instead of stranding a shedding replica in the
        fleet — and the controller keeps retrying its drain (without
        re-checkpointing) until it completes. Returns a summary once
        ``max_ticks`` elapse or ``stop_event`` is set; the event log
        rides ``self.events`` like the training paths.

        Fleet SLO federation (``monitor/federation.py``):
        ``federation`` is a ``FleetSLOView`` over the replicas'
        published telemetry frames — with one, each tick reads frames
        NON-BLOCKING and the ``signals`` callable is only a fallback
        for replicas with no fresh frame. ``fleet_burn_scaling``
        (default ``FLAGS_serving_fleet_burn_scaling``, OFF — flags-off
        decisions byte-identical) arms burn-aware actuation: a
        federated latency-objective fast-burn adds one replica of
        scale-out pressure even at flat demand, and scale-in is
        REFUSED while the fleet burn alerts (latency objectives only —
        the shed-on-burn ``load_only`` lesson: availability-fed
        triggers self-lock; already-committed drains keep retrying).
        With the flag on and no view passed, one is built over
        ``heartbeat_dir``. ``signal_timeout`` bounds every USER-PASSED
        ``signals`` call on a worker thread (None/<=0 = unbounded):
        one frozen replica's callable delays a tick by at most the
        bound ONCE — while its call is still outstanding the replica
        is skipped (payload None), so heartbeat checks and scale-out
        for the rest of the fleet keep running. The built-in default
        (a direct in-process ``handle.autoscale_payload()`` read)
        stays inline — it cannot wedge on a transport, and bounding
        it would cost a thread per replica per tick. Beat hygiene:
        stopping or replacing a replica sweeps its name-keyed beat
        file and frame (``heartbeat.remove_named``), and spawning one
        sweeps any leftover from a PRIOR controller incarnation (a
        higher-seq dead frame would otherwise outrank the fresh
        replica's), so a long-lived controller dir does not
        accumulate dead replicas' files.

        Exactly-once failover (``inference/failover.py``): with
        ``failover`` on (default ``FLAGS_serving_failover``, OFF —
        flags-off decisions byte-identical), the controller owns a
        :class:`~paddle_tpu.inference.failover.FailoverCoordinator`
        (exposed as ``self.failover_coordinator`` and registered for
        the ``/fleet/serving`` failover block). When a stale replica
        is force-replaced, the coordinator consumes its admission
        journal — completion markers dedup work that finished just
        before the crash — and queues the stranded remainder for
        re-dispatch; the caller's pump (``on_tick``) drains
        ``coordinator.due()`` through normal admission on survivors.
        Spawning and retiring a replica sweeps its journal alongside
        its beat/frame (same hygiene contract).

        ``on_tick(ticks, replicas)`` is an optional in-process hook
        called at the top of every tick on the controller thread —
        the loadgen trace-replay pump rides it to submit work and
        step in-process engines in lockstep with the controller's
        spawn/stop decisions. Exceptions are recorded as events,
        never fatal."""
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{min_replicas}, {max_replicas}]")
        from .. import heartbeat as _heartbeat

        default_signals = signals is None
        if signals is None:
            def signals(name, h):
                return h.autoscale_payload() \
                    if hasattr(h, "autoscale_payload") else None
        if drain is None:
            def drain(name, h):
                if hasattr(h, "begin_drain"):
                    h.begin_drain()
        # the built-in default is a direct in-process attribute read —
        # it cannot wedge on a remote transport, and bounding it would
        # spawn a worker thread per replica per tick on the 50ms
        # control loop for nothing; pass-through keeps the pre-bound
        # inline semantics (discard_pending stays a no-op)
        signals = _BoundedSignals(
            signals, None if default_signals else signal_timeout)
        from ...core import flags as _cflags
        burn_scaling = bool(
            _cflags.flag_value("serving_fleet_burn_scaling")
            if fleet_burn_scaling is None else fleet_burn_scaling)
        failover_on = bool(
            _cflags.flag_value("serving_failover")
            if failover is None else failover)
        coord = None
        _fo = None
        if failover_on:
            from ...inference import failover as _fo
            coord = _fo.FailoverCoordinator(heartbeat_dir=heartbeat_dir)
            self.failover_coordinator = coord
            _fo.set_active_coordinator(coord)
        view = federation
        if view is None and burn_scaling and heartbeat_dir:
            from ...monitor import federation as _fed
            view = _fed.FleetSLOView(heartbeat_dir)
        if view is not None:
            from ...monitor import federation as _fed
            _fed.set_active_view(view)
        # burn-actuation edge trackers (events record transitions, not
        # every tick)
        self._burn_pressure_on = False
        self._burn_refused_on = False
        self.restarts = 0
        self.events = []
        if burn_scaling and view is None:
            # the flag promises burn-aware actuation, but with no
            # federation view and no heartbeat_dir to build one over
            # there is no telemetry to act on — burn_alert stays False
            # forever and decisions degrade to demand-only scaling.
            # Record the misconfiguration ONCE instead of silently
            # behaving as if the flag were off.
            self._record(ElasticStatus.RESTART,
                         {"reason": "burn-scaling-no-telemetry",
                          "detail": "FLAGS_serving_fleet_burn_scaling "
                                    "is on but no federation view was "
                                    "passed and no heartbeat_dir is "
                                    "set — burn-aware scale-out/"
                                    "scale-in refusal cannot engage"})
        replicas: dict = {}
        spawn_times: dict = {}
        next_idx = [0]

        def _spawn(reason):
            name = f"replica{next_idx[0]}"
            next_idx[0] += 1
            _sweep_name(name)
            replicas[name] = spawn(name)
            spawn_times[name] = time.time()
            self._record(ElasticStatus.RESTART,
                         {"reason": reason, "replica": name,
                          "replicas": len(replicas)})
            return name

        def _sweep_name(name):
            # transport-only name sweep: the global beat file + KV
            # frame, and the view's OWN transport (a custom client /
            # KV-only fleet the global-client remove_named cannot
            # reach). At spawn time (numbering restarts at replica0
            # every run) this clears a prior incarnation's leftover
            # payload — its HIGHER seq would keep winning read_named's
            # tiebreak, stamped fresh for one staleness window, then
            # masking the live replica's frames until its seq caught
            # up. In-memory view tracking is deliberately untouched
            # here (in-process frame seeding for a name about to
            # spawn is a supported pattern).
            if heartbeat_dir:
                _heartbeat.remove_named(heartbeat_dir, name)
            if coord is not None:
                # same leftover-payload hazard for the admission
                # journal: a prior incarnation's higher-seq journal
                # would win read_named's tiebreak and re-dispatch a
                # dead fleet's requests into this one
                _fo.sweep_journal(name, dir_path=heartbeat_dir)
            if view is not None:
                view.sweep(name)

        def _gc_replica(name):
            # beat-file + frame GC for a name that will NEVER be
            # asked again (stopped or replaced; numbering is
            # monotonic). One edit-wide contract for both retirement
            # paths: the global transport, the view's OWN transport
            # (custom client / KV-only fleets the global-client
            # remove_named cannot reach) + its tracking, and the
            # bounded-signals worker (a wedged call's pending box
            # would pin the stopped replica's handle; its worker
            # thread would idle for the rest of the run)
            _sweep_name(name)
            if view is not None:
                view.forget(name)
            signals.retire(name)

        for _ in range(min_replicas):
            _spawn("spawn")
        ticks = 0
        draining: set = set()    # committed drains: shedding, excluded
        #                          from effective capacity, retried
        ckpted: set = set()      # victims whose pre-drain checkpoint
        #                          already committed (never re-saved)
        drain_deadline: dict = {}   # name -> [cross-tick deadline,
        #                             timeout-event-recorded flag]
        while True:
            if stop_event is not None and stop_event.is_set():
                self._record(ElasticStatus.EXIT, {"reason": "stopped"})
                break
            if max_ticks is not None and ticks >= max_ticks:
                self._record(ElasticStatus.EXIT,
                             {"reason": "max_ticks", "ticks": ticks})
                break
            ticks += 1
            if on_tick is not None:
                # in-process pump hook (the loadgen replay driver):
                # runs ON the controller thread, ordered with this
                # tick's stale handling and scaling decisions — the
                # caller submits work / steps in-process engines here
                # without feeder-thread races. A raising hook is a
                # caller bug: recorded, never fatal to the fleet.
                try:
                    on_tick(ticks, dict(replicas))
                except Exception as e:
                    self._record(ElasticStatus.ERROR,
                                 {"reason": "on-tick-error",
                                  "detail": repr(e)[:300]})
            if heartbeat_dir and heartbeat_timeout > 0:
                stale = _heartbeat.stale_names(
                    heartbeat_dir, list(replicas), heartbeat_timeout,
                    started_at=spawn_times)
                for name, why in stale.items():
                    # a wedged replica cannot drain — force-stop and
                    # replace, burning a unit of the restart budget
                    handle = replicas.pop(name)
                    spawn_times.pop(name, None)
                    draining.discard(name)
                    ckpted.discard(name)
                    drain_deadline.pop(name, None)
                    self._record(ElasticStatus.RESTART,
                                 {"reason": "stale-replace",
                                  "replica": name, "detail": why})
                    try:
                        stop(name, handle)
                    except Exception as e:
                        self._record(ElasticStatus.ERROR,
                                     {"reason": "stale-stop-failed",
                                      "replica": name,
                                      "detail": repr(e)})
                    if coord is not None:
                        # consume the dead replica's admission journal
                        # BEFORE the GC sweeps it: completion markers
                        # dedup, poison requests quarantine, the rest
                        # queue for re-dispatch on survivors
                        stranded = coord.note_replaced(name)
                        if stranded:
                            self._record(
                                ElasticStatus.RESTART,
                                {"reason": "failover-strand",
                                 "replica": name,
                                 "stranded": stranded})
                    # GC AFTER the stop: a stale-but-recovering
                    # replica could otherwise republish between
                    # sweep and stop, resurrecting an orphan file
                    # for a name no longer tracked
                    _gc_replica(name)
                    self.restarts += 1
                    # >= : same budget semantics as the training paths
                    # (max_restarts replacements total, not N+1)
                    if self.restarts >= self.max_restarts:
                        self._record(
                            ElasticStatus.ERROR,
                            {"reason": "restart budget exhausted"})
                        return {"replicas": list(replicas),
                                "ticks": ticks, "events": self.events}
            fed_fresh = {}
            burn_alert = False
            if view is not None:
                # NON-BLOCKING telemetry: published frames answer for
                # every replica with a fresh one; the signals callable
                # is only the fallback below
                try:
                    view.poll(list(replicas))
                    fed_fresh = view.fresh_frames(list(replicas))
                    if burn_scaling:
                        rep = view.fleet_report(list(replicas),
                                                poll=False)
                        burn_alert = bool(rep["alerting_load"])
                except Exception:
                    fed_fresh = {}
            payloads = {}
            for name, h in list(replicas.items()):
                frame = fed_fresh.get(name)
                if frame is not None:
                    # frame sub-blocks are remote input: a truthy
                    # non-dict autoscale must contribute nothing, not
                    # crash the tick
                    p = frame.get("autoscale")
                    if not isinstance(p, dict):
                        p = None
                else:
                    try:
                        p = signals(name, h)
                    except Exception:
                        p = None
                if p:
                    payloads[name] = p
            if payloads:
                import math as _math
                # frame payloads are remote input: a malformed
                # demand_estimate (a string, NaN) from one replica
                # contributes nothing — it must not crash the fold or
                # poison the fleet sum
                demand = 0.0
                for p in payloads.values():
                    try:
                        d = float(p.get("demand_estimate", 0.0))
                    except (TypeError, ValueError):
                        continue
                    if _math.isfinite(d):
                        demand += d
                desired = max(int(_math.ceil(demand - 1e-9)), 0)
            else:
                # no signals: hold effective capacity steady
                desired = len(replicas) - len(draining)
            desired = min(max(desired, min_replicas), max_replicas)
            if burn_scaling and burn_alert:
                # fleet latency fast-burn = the current capacity is
                # not meeting the SLO even when demand looks flat: one
                # replica of scale-out pressure over the demand-based
                # target (stable while the burn persists — pressure is
                # +1 over demand, not +1 over capacity per tick, so it
                # cannot escalate to max_replicas on its own)
                desired = min(desired + 1, max_replicas)
                if not self._burn_pressure_on:
                    self._burn_pressure_on = True
                    self._record(ElasticStatus.RESTART,
                                 {"reason": "burn-pressure",
                                  "desired": desired})
            else:
                # burn cleared (or scaling off): re-arm the
                # once-per-episode transition events
                self._burn_pressure_on = False
                self._burn_refused_on = False
            # effective capacity excludes committed drains: a replica
            # that began draining sheds every submission, so demand
            # growth mid-drain spawns a replacement instead of
            # counting a shedding replica as capacity. The TOTAL fleet
            # (draining included) still honors max_replicas — on infra
            # provisioned for exactly that many, the replacement waits
            # for the drain to land rather than oversubscribing.
            while (len(replicas) - len(draining) < desired
                   and len(replicas) < max_replicas):
                _spawn("scale-out")
            target = None
            if draining:
                # resume a committed drain first (no re-checkpoint)
                target = next(n for n in replicas if n in draining)
            elif len(replicas) - len(draining) > desired:
                if burn_scaling and burn_alert:
                    # scale-in REFUSED while the fleet burn alerts:
                    # shrinking a fleet that is failing its latency
                    # SLO digs the hole deeper. Latency objectives
                    # only (load_only above) — the refusal itself can
                    # never feed the trigger that caused it.
                    if not self._burn_refused_on:
                        self._burn_refused_on = True
                        self._record(ElasticStatus.RESTART,
                                     {"reason": "burn-scale-in-refused",
                                      "desired": desired})
                else:
                    # (the burn-cleared else above already re-armed
                    # the refused-episode tracker this tick)
                    target = next(n for n in reversed(list(replicas))
                                  if n not in draining)  # newest first
            if target is not None:
                if target not in draining:
                    draining.add(target)
                    drain_deadline[target] = [
                        time.monotonic() + drain_timeout, False]
                # the in-tick wait is BOUNDED (~one poll interval):
                # the drain itself persists across ticks via the sets
                # above, so a slow drain never suspends heartbeat
                # checks, demand gathering, or scale-out for the rest
                # of the fleet; drain_timeout is accounted against the
                # cross-tick deadline instead
                ok = self._drain_and_stop(
                    target, replicas[target], signals=signals,
                    drain=drain, stop=stop,
                    drain_timeout=poll_interval,
                    poll_interval=poll_interval, state_fn=state_fn,
                    ckpt_dir=ckpt_dir,
                    checkpoint=target not in ckpted,
                    discard_stale_signals=target not in ckpted,
                    stop_event=stop_event, view=view)
                ckpted.add(target)
                if ok:
                    replicas.pop(target)
                    spawn_times.pop(target, None)
                    draining.discard(target)
                    ckpted.discard(target)
                    drain_deadline.pop(target, None)
                    _gc_replica(target)
                    self._record(ElasticStatus.RESTART,
                                 {"reason": "scale-in",
                                  "replica": target,
                                  "replicas": len(replicas)})
                else:
                    dl = drain_deadline.get(target)
                    if dl and not dl[1] and time.monotonic() >= dl[0]:
                        # cross-tick drain_timeout spent: record the
                        # transition ONCE (informational — the drain
                        # stays committed and keeps retrying)
                        dl[1] = True
                        self._record(ElasticStatus.RESTART,
                                     {"reason": "drain-timeout",
                                      "replica": target})
            if stop_event is not None:
                stop_event.wait(poll_interval)
            else:
                time.sleep(poll_interval)
        out = {"replicas": list(replicas), "ticks": ticks,
               "events": self.events}
        if coord is not None:
            out["failover"] = coord.snapshot()
        return out


# -- worker-side elastic state (resume across world re-forms) ----------------

def elastic_run_index() -> int:
    """Which world incarnation this process belongs to (0 = first)."""
    import os
    return int(os.environ.get("PADDLE_ELASTIC_RUN", "0"))


# One CheckpointManager per checkpoint root (workers call save_state /
# load_state with only the env var set; the manager carries the commit
# protocol, retention, discovery, and the SIGTERM finalize hook).
_MANAGERS: dict = {}


def _manager_for(root: str):
    import os

    mgr = _MANAGERS.get(root)
    if mgr is None:
        from ..checkpoint import CheckpointManager

        mgr = CheckpointManager(
            root,
            keep_last_n=int(os.environ.get("PADDLE_ELASTIC_KEEP_CKPTS",
                                           "2")),
            async_save=True)
        # preemption (launcher fail-fast SIGTERM): finalize the
        # in-flight save — or take an emergency sync save — before
        # dying, so the restarted world resumes from the step the
        # worker was actually on
        mgr.install_preemption_hook()
        _MANAGERS[root] = mgr
    return mgr


def save_state(step: int, state_dict, blocking: bool = False,
               prev_handle=None):
    """Checkpoint one training step for elastic resume via the
    CheckpointManager: atomic commit (a kill mid-write can never be
    resumed from), async by default (snapshot now, write in background,
    shard-aware, reshards on load at a different world size), keep-last-N
    retention. The manager itself finalizes the previous save before
    staging the next (a 1-deep pipeline: step N's save overlaps step
    N+1's compute), so ``prev_handle`` is accepted only for backward
    compatibility and ignored.

    Returns the per-root manager (or None when blocking or no
    PADDLE_ELASTIC_CKPT_DIR is set); pass whatever was returned to
    ``finish_saves`` once after the loop to join the final save."""
    import os

    root = os.environ.get("PADDLE_ELASTIC_CKPT_DIR")
    if not root:
        return None
    mgr = _manager_for(root)
    mgr.save(step, dict(state_dict), blocking=blocking)
    return None if blocking else mgr


def finish_saves(pending) -> bool:
    """Finalize an in-flight elastic save (join + retention GC)."""
    if pending is None:
        return False
    pending.wait()
    return True


def load_state(template_state_dict):
    """Resume point for an elastic worker: (start_step, state). Loads the
    newest COMMITTED checkpoint into ``template_state_dict`` (sharded
    values reshard to the CURRENT world's placements), skipping
    incomplete or corrupt directories, or returns (0, template) on a
    fresh start. Falls back to a pre-commit-protocol layout (``latest``
    pointer + ``step<N>`` dirs) so jobs upgraded mid-flight keep their
    resume point."""
    import os

    root = os.environ.get("PADDLE_ELASTIC_CKPT_DIR")
    if not root or not os.path.isdir(root):
        return 0, template_state_dict
    mgr = _manager_for(root)
    full = dict(template_state_dict)
    step = mgr.restore_latest(full)
    if step is None:
        legacy = _load_legacy_state(root, template_state_dict)
        if legacy is not None:
            return legacy
        return 0, template_state_dict
    return step, full


def _load_legacy_state(root, template_state_dict):
    """Resume from a checkpoint dir written before the commit protocol:
    the old rank-0 ``latest`` pointer named the ``step<N>`` dir (no
    underscore, no COMMIT/manifest). Best-effort — any failure means a
    fresh start, as before."""
    import os

    latest = os.path.join(root, "latest")
    if not os.path.isfile(latest):
        return None
    from .. import checkpoint as dckpt

    try:
        step = int(open(latest).read().strip())
        full = dict(template_state_dict)
        dckpt.load_state_dict(full, os.path.join(root, f"step{step}"),
                              verify=False)
        return step, full
    except Exception as e:
        import sys
        print(f"[elastic] legacy checkpoint at {root!r} unusable "
              f"({type(e).__name__}: {e}); starting fresh", file=sys.stderr)
        return None
