"""Placement vocabulary for distributed tensors.

Reference: paddle/phi/core/distributed/auto_parallel/placement_types.h:36-132
(Placement / Shard / Replicate / Partial) and python/paddle/distributed
(Shard, Replicate, Partial, ReduceType exports).

TPU-native design: a placement list over mesh dims compiles down to a
``jax.sharding.NamedSharding`` (PartitionSpec). ``Partial`` has no direct
GSPMD storage type — we keep it as an annotation on the Tensor handle and
materialize the pending reduction (psum over the mesh axis) when resharding
to Replicate/Shard, exactly mirroring the reference's p_to_r / p_to_s
reshard functions (phi/core/distributed/auto_parallel/reshard/).
"""
from __future__ import annotations

import enum

__all__ = ["Placement", "Shard", "Replicate", "Partial", "ReduceType"]


class ReduceType(enum.Enum):
    """Reference: placement_types.h ReduceType enum (kRedSum..kRedAll)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class Placement:
    """Base placement (placement_types.h:36)."""

    def is_shard(self, dim=None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self).__name__)


class Replicate(Placement):
    def is_replicated(self) -> bool:
        return True

    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    """Shard(dim): tensor dim ``dim`` is split across the mesh dim this
    placement is attached to."""

    def __init__(self, dim: int):
        self.dim = int(dim)

    def get_dim(self) -> int:
        return self.dim

    def is_shard(self, dim=None) -> bool:
        return dim is None or dim == self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    """Pending-reduction placement (each shard holds a partial value)."""

    def __init__(self, reduce_type: ReduceType = ReduceType.kRedSum):
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type.name})"
