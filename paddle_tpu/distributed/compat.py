"""Distributed surface completion: mp split, auto-parallel Strategy /
DistModel / to_static, ParallelMode, gloo shims, PS-era dataset gates.

Reference capability: python/paddle/distributed/auto_parallel/api.py
(Strategy, DistModel, to_static), fleet/base/topology.py ParallelMode,
collective split (fleet/layers/mpu), parallel.py gloo_* helpers,
fleet InMemoryDataset/QueueDataset + entry configs (PS pipeline).

TPU-native: split is a GSPMD sharding over the current mesh; DistModel
wraps the jitted sharded train step (the single-controller equivalent of
the reference's static Engine-backed DistModel); gloo barriers map to the
single-controller barrier.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from ..core import enforce as E

__all__ = [
    "ParallelMode", "split", "Strategy", "DistAttr", "DistModel",
    "to_static", "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
    "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry",
]


class ParallelMode:
    """reference: parallel.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style split layer builder (reference:
    collective.split / fleet mpu): builds a column/row-parallel linear or
    a vocab-parallel embedding sharded over the model-parallel axis."""
    from ..distributed import fleet

    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = fleet.meta_parallel.ColumnParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False, gather_output=gather_out)
        else:
            layer = fleet.meta_parallel.RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False)
        return layer(x)
    if operation == "embedding":
        vocab, emb = size
        layer = fleet.meta_parallel.VocabParallelEmbedding(
            vocab, emb, weight_attr=weight_attr)
        return layer(x)
    raise E.InvalidArgumentError(f"split: unknown operation {operation!r}")


class Strategy:
    """Auto-parallel strategy config (reference:
    auto_parallel/strategy.py Strategy): nested option groups as plain
    attribute namespaces."""

    class _Group:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    def __init__(self, config=None):
        self.sharding = self._Group(enable=False, stage=1, degree=8)
        self.fused_passes = self._Group(enable=False, fused_passes_list=[])
        self.gradient_merge = self._Group(enable=False, k_steps=1,
                                          avg=True)
        self.pipeline = self._Group(enable=False, schedule_mode="1F1B",
                                    micro_batch_size=1,
                                    accumulate_steps=1)
        self.amp = self._Group(enable=False, dtype="float16", level="O1")
        self.recompute = self._Group(enable=False)
        if config:
            for k, v in config.items():
                grp = getattr(self, k, None)
                if grp is not None and isinstance(v, dict):
                    grp.__dict__.update(v)


class DistAttr:
    """Tensor distributed attribute (reference:
    auto_parallel/api.py DistAttr): a (mesh, placements) pair."""

    def __init__(self, mesh=None, sharding_specs=None, placements=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs
        self.placements = placements


class DistModel:
    """reference: auto_parallel/api.py DistModel (via to_static): wraps a
    layer + loader + loss + optimizer into a sharded compiled step with
    train()/eval()/predict() mode switches and __call__ dispatch."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self.network = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._mode = "train" if optimizer is not None else (
            "eval" if loss is not None else "predict")

    def train(self):
        self._mode = "train"
        self.network.train()

    def eval(self):
        self._mode = "eval"
        self.network.eval()

    def predict(self):
        self._mode = "predict"
        self.network.eval()

    def dist_main_program(self, mode=None):
        return None   # single-controller: no static partitioned program

    def __call__(self, *args):
        if self._mode == "predict":
            return self.network(*args)
        inputs, labels = args[:-1], args[-1]
        out = self.network(*inputs)
        loss = self._loss(out, labels)
        if self._mode == "train":
            loss.backward()
            self._optimizer.step()
            self._optimizer.clear_grad()
        return loss

    def state_dict(self, mode="all"):
        return self.network.state_dict()

    def set_state_dict(self, state_dict):
        return self.network.set_state_dict(state_dict)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              metrics=None):
    """reference: auto_parallel/api.py to_static — returns (DistModel,
    loader). The mesh/shardings already annotated on the layer's
    parameters (shard_tensor/shard_layer) drive GSPMD when the caller
    jits; the DistModel wrapper provides the mode/step surface."""
    model = DistModel(layer, loader, loss, optimizer, strategy, metrics)
    return model, loader


# -- gloo shims (reference: parallel.py gloo_* for CPU barriers) ------------

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Single-controller runtime: cross-process rendezvous is
    jax.distributed (distributed.env.init_parallel_env); gloo is not a
    separate backend here."""
    return None


def gloo_barrier():
    from .collective import barrier

    return barrier()


def gloo_release():
    return None


# -- PS-era dataset pipeline (out of scope; explicit gates) -----------------

_PS_MSG = ("the parameter-server in-memory dataset pipeline is out of "
           "scope for this TPU-native runtime (docs/CAPABILITY_DELTA.md); "
           "use paddle.io.DataLoader with subprocess workers")


class InMemoryDataset:
    def __init__(self, *a, **k):
        raise NotImplementedError(_PS_MSG)


class QueueDataset:
    def __init__(self, *a, **k):
        raise NotImplementedError(_PS_MSG)


class CountFilterEntry:
    def __init__(self, *a, **k):
        raise NotImplementedError(_PS_MSG)


class ProbabilityEntry:
    def __init__(self, *a, **k):
        raise NotImplementedError(_PS_MSG)


class ShowClickEntry:
    def __init__(self, *a, **k):
        raise NotImplementedError(_PS_MSG)
