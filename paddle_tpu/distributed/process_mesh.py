"""ProcessMesh: the device-mesh abstraction.

Reference: python/paddle/distributed/auto_parallel/process_mesh.py
(ProcessMesh) + paddle/phi/core/distributed/auto_parallel (mesh in
TensorDistAttr). TPU-native design: a ProcessMesh *is* a
``jax.sharding.Mesh`` over real (or virtual host-platform) devices; axis
names carry the parallelism semantics (dp/mp/pp/sep/...). Placement lists
compile to ``NamedSharding(mesh, PartitionSpec(...))`` — GSPMD then inserts
the ICI collectives (SURVEY.md §7: "DistTensor+SPMD rules+reshard → jax.Array
+ NamedSharding").
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .placement import Partial, Placement, Replicate, Shard
from ..core import enforce as E

__all__ = ["ProcessMesh", "get_mesh", "set_mesh", "auto_mesh"]

_global_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    """An n-dimensional grid of processes/devices with named dims.

    ``mesh`` is an array of global device ids (ranks); ``dim_names`` names
    each grid axis. Unlike the reference (where ranks map to NCCL group
    members), here ranks index ``jax.devices()`` and the mesh lowers to an
    XLA device assignment.
    """

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 shape: Optional[Sequence[int]] = None,
                 process_ids: Optional[Sequence[int]] = None):
        if mesh is None and shape is not None:
            mesh = np.array(process_ids if process_ids is not None
                            else range(int(np.prod(shape)))).reshape(shape)
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise E.InvalidArgumentError(
                f"dim_names {dim_names} does not match mesh ndim {arr.ndim}")
        self._mesh = arr
        self._dim_names = list(dim_names)
        self._jax_mesh: Optional[Mesh] = None

    # -- paddle-parity accessors --------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._mesh.shape)

    @property
    def ndim(self) -> int:
        return self._mesh.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return [int(i) for i in self._mesh.flatten()]

    @property
    def mesh(self):
        return self._mesh

    @property
    def size(self) -> int:
        return int(self._mesh.size)

    def get_dim_size(self, dim_name: str) -> int:
        return self._mesh.shape[self._dim_names.index(dim_name)]

    def get_mesh_with_dim(self, dim_name: str, index=None):
        """Reference: process_mesh.py get_mesh_with_dim — reorder so
        ``dim_name`` is the leading axis; with ``index``, slice it out."""
        axis = self._dim_names.index(dim_name)
        order = [axis] + [i for i in range(self.ndim) if i != axis]
        new_mesh = self._mesh.transpose(order)
        names = [self._dim_names[i] for i in order]
        if index is None:
            return ProcessMesh(new_mesh, names)
        return ProcessMesh(new_mesh[index], names[1:])

    # -- jax lowering --------------------------------------------------------
    def jax_mesh(self) -> Mesh:
        """Materialize as jax.sharding.Mesh (cached)."""
        if self._jax_mesh is None:
            devices = jax.devices()
            if self.size > len(devices):
                raise E.PreconditionNotMetError(
                    f"ProcessMesh needs {self.size} devices, only "
                    f"{len(devices)} visible. For tests use "
                    f"--xla_force_host_platform_device_count.")
            dev_grid = np.asarray(
                [devices[i] for i in self._mesh.flatten()]
            ).reshape(self._mesh.shape)
            self._jax_mesh = Mesh(dev_grid, axis_names=tuple(self._dim_names))
        return self._jax_mesh

    def named_sharding(self, placements: Sequence[Placement],
                       ndim: Optional[int] = None) -> NamedSharding:
        """Compile a placement list (one entry per mesh dim) to NamedSharding.

        Partial placements shard nothing (the pending-reduce annotation lives
        on the Tensor handle; see placement.py docstring).
        """
        spec = placements_to_spec(placements, self._dim_names)
        return NamedSharding(self.jax_mesh(), spec)

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh, other._mesh)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self._dim_names})")


def placements_to_spec(placements: Sequence[Placement],
                       dim_names: Sequence[str]) -> PartitionSpec:
    """[Shard(0), Replicate()] over mesh dims (a, b) -> PartitionSpec(('a',)).

    Multiple mesh dims sharding the same tensor dim stack into a tuple in
    mesh-dim order (matches GSPMD's multi-axis sharding and the reference's
    nd-mesh shardings).
    """
    by_tensor_dim = {}
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            by_tensor_dim.setdefault(p.dim, []).append(dim_names[mesh_dim])
    if not by_tensor_dim:
        return PartitionSpec()
    max_dim = max(by_tensor_dim)
    entries = []
    for d in range(max_dim + 1):
        axes = by_tensor_dim.get(d)
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return PartitionSpec(*entries)


def spec_to_placements(spec: PartitionSpec, mesh: ProcessMesh):
    """Inverse of placements_to_spec (best-effort; Partial not represented)."""
    placements = [Replicate() for _ in range(mesh.ndim)]
    for tdim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[mesh.dim_names.index(ax)] = Shard(tdim)
    return placements


def set_mesh(mesh: ProcessMesh):
    """Reference: paddle.distributed.auto_parallel.set_mesh."""
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


def auto_mesh(*dim_sizes, dim_names: Optional[Sequence[str]] = None) -> ProcessMesh:
    """Convenience: build a mesh over the first prod(dim_sizes) devices."""
    shape = tuple(int(s) for s in dim_sizes)
    return ProcessMesh(np.arange(int(np.prod(shape))).reshape(shape), dim_names)
