"""paddle.distributed parity surface.

Reference export list: python/paddle/distributed/__init__.py (SURVEY.md §2.6
"Public paddle.distributed API (parity checklist)").

Layering (TPU-native):
  env.py          — rank/world/init over the jax.distributed coordination svc
  process_mesh.py — ProcessMesh -> jax.sharding.Mesh
  placement.py    — Shard/Replicate/Partial vocabulary
  api.py          — shard_tensor/reshard/shard_layer/shard_optimizer (DistTensor
                    = jax.Array + NamedSharding)
  collective.py   — process groups + eager/host collectives
  comm_ops.py     — compiled collectives (lax.psum/all_gather/ppermute) — the
                    actual ICI/DCN backend
  fleet/          — hybrid-parallel programming model (topology, mp layers)
"""
from . import comm_ops  # noqa
from .api import (ShardingStage1, ShardingStage2, ShardingStage3,  # noqa
                  dtensor_from_fn, per_device_bytes, reshard,
                  shard_dataloader, shard_layer, shard_optimizer,
                  shard_scaler, shard_tensor, unshard_dtensor)
from .collective import (Group, ReduceOp, all_gather, all_gather_object,  # noqa
                         all_reduce, alltoall, alltoall_single, barrier,
                         broadcast, broadcast_object_list,
                         destroy_process_group, gather, get_backend,
                         get_group, irecv, is_available, isend, new_group,
                         P2POp, batch_isend_irecv,
                         recv, reduce, reduce_scatter, scatter,
                         scatter_object_list, send, wait,
                         CollectiveTimeout, PeerLostError,
                         PEER_FAILURE_RC, COLLECTIVE_TIMEOUT_RC,
                         abort_on_collective_fault, coordinated_abort)
from .env import (ParallelEnv, get_rank, get_world_size,  # noqa
                  init_parallel_env, is_initialized)
from .placement import Partial, Placement, ReduceType, Replicate, Shard  # noqa
from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa

from . import fleet  # noqa  (hybrid-parallel programming model)
from . import launch  # noqa  (the launch CLI: python -m ...distributed.launch)
from . import pipeline  # noqa  (collective-permute PP schedules)
from .spawn import spawn  # noqa
from .parallel import DataParallel  # noqa
from . import checkpoint  # noqa
from .checkpoint import (CheckpointManager, load_state_dict,  # noqa
                         save_state_dict)
from . import io  # noqa
from .compat import (CountFilterEntry, DistAttr, DistModel,  # noqa
                     InMemoryDataset, ParallelMode, ProbabilityEntry,
                     QueueDataset, ShowClickEntry, Strategy, gloo_barrier,
                     gloo_init_parallel_env, gloo_release, split, to_static)

from . import engine  # noqa: F401,E402
from .engine import Engine, ParallelPlan, plan_parallel  # noqa: F401,E402
from . import introspect  # noqa: F401,E402  (sharding-layout inspector)
from . import sharding  # noqa: F401,E402
from .sharding import (group_sharded_parallel,  # noqa: F401,E402
                       save_group_sharded_model)
from . import stream  # noqa: F401,E402


def shard_op(op, process_mesh=None, in_shard_specs=None,
             out_shard_specs=None):
    """Annotate an op/callable for auto-parallel (reference:
    distributed/auto_parallel/static/api shard_op). Under GSPMD the
    partitioner derives op shardings from operand shardings, so this
    returns the callable unchanged after validating the mesh."""
    return op
