"""paddle.distributed parity surface — phase-5 build-out in progress.

Reference export list: python/paddle/distributed/__init__.py (SURVEY.md §2.6).
"""
from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env,  # noqa
                  is_initialized)
