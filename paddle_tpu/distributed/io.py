"""paddle.distributed.io: persistable save/load for distributed programs.

Reference capability: python/paddle/distributed/io.py (save_persistables
:392, load_persistables:132, is_persistable:357,
load_inference_model_distributed:464). The reference walks static-program
persistable vars; here persistables are the static Program's captured
eager Parameters, and the sharded-tensor path delegates to
distributed.checkpoint (reshard-on-load)."""
from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

__all__ = ["save_persistables", "load_persistables", "is_persistable",
           "load_inference_model_distributed"]


def is_persistable(var):
    from ..core.tensor import Parameter

    if isinstance(var, Parameter):
        return True
    return bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import default_main_program

    prog = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    state = {f"p{i}": np.asarray(p._data)
             for i, p in enumerate(prog._params())}
    path = os.path.join(dirname, filename or "__persistables__.npz")
    np.savez(path, **state)
    return path


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import default_main_program

    prog = main_program or default_main_program()
    path = os.path.join(dirname, filename or "__persistables__.npz")
    loaded = np.load(path)
    for i, p in enumerate(prog._params()):
        key = f"p{i}"
        if key in loaded:
            p._data = jnp.asarray(loaded[key]).astype(p._data.dtype)


def load_inference_model_distributed(dirname, executor, model_filename=None,
                                     params_filename=None):
    from ..static import load_inference_model

    return load_inference_model(dirname, executor)
