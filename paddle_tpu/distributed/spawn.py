"""paddle.distributed.spawn parity.

Reference: python/paddle/distributed/spawn.py — run ``func(*args)`` in
``nprocs`` fresh processes with the rendezvous env prepared. Uses the
'spawn' start method so each worker gets a clean interpreter (jax must
initialize per process).
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Sequence
from ..core import enforce as E

__all__ = ["spawn"]


def _worker(func, args, rank, nprocs, master, backend):
    os.environ.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_RANK_IN_NODE": str(rank),
        "PADDLE_LOCAL_SIZE": str(nprocs),
        "PADDLE_MASTER": master,
        "MASTER_ADDR": master.rsplit(":", 1)[0],
        "MASTER_PORT": master.rsplit(":", 1)[1],
    })
    func(*args)


def spawn(func, args: Sequence = (), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    """Reference: spawn(func, args, nprocs, join). Returns the context
    (list of processes) when join=False."""
    from .launch.main import _free_port
    master = options.get("master") or f"127.0.0.1:{_free_port()}"
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, tuple(args), rank, nprocs, master,
                              options.get("backend", "xla")),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    failed = []
    for p in procs:
        p.join()
        if p.exitcode != 0:
            failed.append(p.exitcode)
    if failed:
        raise E.PreconditionNotMetError(
            f"spawn: {len(failed)} worker(s) failed with exit codes "
            f"{failed}")
    return procs
