"""Sharding-layout inspector: what does the mesh actually hold?

GSPMD sharding (PAPERS.md) is declared leaf-by-leaf as PartitionSpecs
and then *disappears* into the compiler — nothing at runtime says how
a parameter tree is laid out, how many bytes each device carries, or
whether one axis choice silently replicated a 2 GB embedding onto
every chip. This module answers those questions for any pytree of
(possibly sharded) arrays:

- :func:`describe_leaf` — per-leaf PartitionSpec, mesh axes, global
  vs per-device shard bytes, replication factor
  (``devices x shard_elems / global_elems``; 1 = fully partitioned,
  ``num_devices`` = fully replicated), and whether the leaf is fully
  replicated.
- :func:`describe_tree` — bounded per-leaf report plus totals and a
  **cross-device imbalance summary**: per-device byte totals (summed
  over the leaves' actual shards) with ``(max - min) / max`` — uneven
  sharding of a 4D-parallel tree shows up as one number.
- :func:`register_sharded_tree` / :func:`sharding_snapshot` — the
  ``/sharding`` endpoint's feed: explicitly registered trees (the
  serving engine registers its params; training loops can register
  theirs) merged with the per-program argument-sharding summaries the
  introspection registry captured at the ``jit/api.py`` cache-miss
  seam and the engine's prefill/decode registrations — so a pure
  serving run populates the view with no training loop in sight.

Everything is read-only and backend-safe: a leaf without a
``.sharding`` (numpy input, scalar) reports as unsharded, a dead/
deleted array contributes nothing, and callers gate registration on
``monitor.enabled()`` (the inspector itself registers nothing on the
off path).
"""
from __future__ import annotations

import threading
from typing import Optional

__all__ = ["describe_leaf", "describe_tree", "register_sharded_tree",
           "ensure_sharded_tree", "unregister_sharded_tree",
           "sharding_snapshot", "reset"]

# Per-leaf reports are bounded: a 10k-leaf tree must not turn a scrape
# payload into megabytes. Totals/imbalance still cover every leaf.
_MAX_LEAVES = 256

# Explicitly registered trees: name -> computed summary (bounded FIFO;
# summaries are computed AT registration so the registry never pins
# the arrays themselves).
_MU = threading.Lock()
_TREES: dict = {}
_MAX_TREES = 32


def _leaf_array(x):
    """Unwrap Tensor facades; None for non-arrays."""
    data = getattr(x, "_data", x)
    if hasattr(data, "shape") and hasattr(data, "dtype"):
        return data
    return None


def _path_str(path) -> str:
    import jax
    try:
        return jax.tree_util.keystr(path)
    except Exception:
        return str(path)


def describe_leaf(arr, path: str = "") -> Optional[dict]:
    """Layout facts of one (possibly sharded) array, or None for
    non-array leaves. Never raises — a deleted donated buffer reports
    what it can."""
    import numpy as np

    data = _leaf_array(arr)
    if data is None:
        return None
    try:
        shape = tuple(int(d) for d in data.shape)
        itemsize = np.dtype(data.dtype).itemsize
    except Exception:
        return None
    global_elems = 1
    for d in shape:
        global_elems *= d
    out = {
        "path": path,
        "shape": list(shape),
        "dtype": str(np.dtype(data.dtype).name),
        "global_bytes": global_elems * itemsize,
        "spec": None,
        "mesh_axes": None,
        "num_devices": 1,
        "shard_shape": list(shape),
        "shard_bytes": global_elems * itemsize,
        "replication_factor": 1.0,
        "fully_replicated": True,
    }
    sh = getattr(data, "sharding", None)
    if sh is None:
        return out
    try:
        devs = getattr(sh, "device_set", None)
        n_dev = len(devs) if devs else 1
        out["num_devices"] = n_dev
        spec = getattr(sh, "spec", None)
        if spec is not None:
            out["spec"] = str(spec)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None:
            out["mesh_axes"] = {str(name): int(size) for name, size in
                                zip(mesh.axis_names, mesh.devices.shape)}
        shard_shape = tuple(int(d) for d in sh.shard_shape(shape))
        shard_elems = 1
        for d in shard_shape:
            shard_elems *= d
        out["shard_shape"] = list(shard_shape)
        out["shard_bytes"] = shard_elems * itemsize
        if global_elems > 0:
            out["replication_factor"] = round(
                n_dev * shard_elems / global_elems, 4)
        out["fully_replicated"] = bool(
            getattr(sh, "is_fully_replicated", shard_shape == shape))
    except Exception:
        # an exotic sharding (GSPMD opaque) keeps the global facts
        out["spec"] = out["spec"] or str(sh)
    return out


def describe_tree(tree, max_leaves: int = _MAX_LEAVES) -> dict:
    """Bounded per-leaf layout report + totals + cross-device
    imbalance for a pytree of arrays (Tensor facades unwrapped)."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: _leaf_array(x) is not None)[0]
    leaves = []
    total_global = total_shard = 0
    n_arrays = 0
    per_device: dict = {}
    replicated_bytes = 0
    for path, x in flat:
        d = describe_leaf(x, _path_str(path))
        if d is None:
            continue
        n_arrays += 1
        total_global += d["global_bytes"]
        total_shard += d["shard_bytes"]
        if d["fully_replicated"] and d["num_devices"] > 1:
            replicated_bytes += d["global_bytes"]
        data = _leaf_array(x)
        try:
            import numpy as np
            itemsize = np.dtype(data.dtype).itemsize
            for shard in getattr(data, "addressable_shards", []):
                n = 1
                for dim in shard.data.shape:
                    n *= int(dim)
                dev = str(shard.device)
                per_device[dev] = per_device.get(dev, 0) + n * itemsize
        except Exception:
            pass
        if len(leaves) < max_leaves:
            leaves.append(d)
    imbalance = None
    if per_device:
        vals = list(per_device.values())
        mx, mn = max(vals), min(vals)
        imbalance = {
            "devices": len(per_device),
            "max_device_bytes": mx,
            "min_device_bytes": mn,
            "mean_device_bytes": int(sum(vals) / len(vals)),
            "relative_imbalance": round((mx - mn) / mx, 4)
            if mx > 0 else 0.0,
        }
    return {
        "leaves": leaves,
        "num_arrays": n_arrays,
        "truncated": n_arrays > len(leaves),
        "total_global_bytes": total_global,
        "total_shard_bytes_per_device": total_shard,
        "replicated_bytes": replicated_bytes,
        "imbalance": imbalance,
    }


def register_sharded_tree(name: str, tree) -> Optional[dict]:
    """Compute + retain a named tree's layout summary for the
    ``/sharding`` endpoint (the serving engine registers its params
    here; training loops can register theirs). Self-gated on the
    monitor flag — the off path computes and registers NOTHING.
    Re-registering a name refreshes it; the map is FIFO-bounded."""
    from .. import monitor as _monitor

    if not _monitor.enabled():
        return None
    try:
        summary = describe_tree(tree)
    except Exception:
        return None
    with _MU:
        _TREES.pop(name, None)
        _TREES[name] = summary
        while len(_TREES) > _MAX_TREES:
            _TREES.pop(next(iter(_TREES)))
    return summary


def ensure_sharded_tree(name: str, tree_fn) -> bool:
    """Register ``tree_fn()`` under ``name`` iff it is not already
    registered — the per-dispatch reset-recovery seam (the serving
    engine calls this from its program-registration path, so a
    ``monitor.reset()`` mid-run repopulates ``/sharding`` on the next
    dispatch instead of staying empty forever). Steady-state cost: one
    locked dict lookup; the tree is only materialized (``tree_fn``
    called) when absent. Monitor-gated like registration."""
    from .. import monitor as _monitor

    if not _monitor.enabled():
        return False
    with _MU:
        if name in _TREES:
            return False
    return register_sharded_tree(name, tree_fn()) is not None


def unregister_sharded_tree(name: str):
    with _MU:
        _TREES.pop(name, None)


def sharding_snapshot() -> dict:
    """The ``/sharding`` payload: world shape, explicitly registered
    trees, and the per-program argument-sharding summaries the
    introspection registry captured (serving prefill/decode programs
    and to_static cache misses)."""
    import jax

    from ..monitor import programs as _programs

    try:
        world = {
            "devices": jax.device_count(),
            "local_devices": jax.local_device_count(),
            "process_count": jax.process_count(),
        }
    except Exception:
        world = {}
    progs = []
    for rec in _programs.programs_snapshot():
        if rec.get("sharding") is not None:
            progs.append({"name": rec["name"], "source": rec["source"],
                          "signature": rec["signature"],
                          "sharding": rec["sharding"]})
    with _MU:
        trees = dict(_TREES)
    return {"world": world, "programs": progs, "trees": trees}


def reset():
    with _MU:
        _TREES.clear()
