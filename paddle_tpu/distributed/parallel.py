"""DataParallel wrapper.

Reference: python/paddle/distributed/parallel.py:202 (DataParallel) + the
C++ EagerReducer (collective/reducer.cc) doing bucketed grad allreduce with
backward overlap.

TPU-native redesign: with a dp-sharded batch (shard_dataloader) the
partitioned backward ALREADY produces globally-reduced gradients — GSPMD
inserts the reduce where the batch dim contracts away, overlapping it with
the backward compute the way the reducer's fused buckets do. DataParallel is
therefore an annotation wrapper: it replicates parameters over the mesh and
keeps the reference surface (``no_sync``, ``scale_loss``) meaningful.
"""
from __future__ import annotations

import contextlib

from ..nn.layer.base import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1,
                 find_unused_parameters: bool = False, group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        from .process_mesh import get_mesh
        mesh = get_mesh()
        if mesh is not None:
            from .api import shard_layer
            shard_layer(layers, mesh)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Reference: parallel.py no_sync — skip grad allreduce inside.

        Semantics here: gradient reduction is part of the compiled backward
        over the dp-sharded batch, so accumulated microstep grads are
        already exact — accumulate-then-step under no_sync produces the
        same update as one big batch (tested in
        tests/test_distributed.py::test_no_sync_accumulation_parity).

        Cost note (documented delta): each eager microstep's backward still
        executes its grad reduction — the reduction is fused into the
        compiled backward, not deferrable from Python. To also SAVE the
        per-microstep reduction bandwidth the way the reference's bucketed
        reducer does, jit the whole accumulation loop (paddle_tpu.jit /
        make_train_step with lax.scan over microbatches): XLA then reduces
        once per accumulation window."""
        yield

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__.get("_sub_layers")["_layers"], name)
