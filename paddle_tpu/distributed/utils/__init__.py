"""paddle.distributed.utils parity (log + process helpers)."""
from __future__ import annotations

__all__ = []


def get_logger(level="INFO", name="paddle_tpu.distributed"):
    import logging

    logger = logging.getLogger(name)
    logger.setLevel(level)
    return logger
