"""Distributed environment basics (rank/world-size/init).

Reference: python/paddle/distributed/parallel.py (ParallelEnv, PADDLE_* env
vars). TPU-native: jax.distributed coordination service replaces TCPStore;
env vars keep the same names so launch-CLI parity holds.
"""
from __future__ import annotations

import os

import jax

_initialized = False


def get_rank() -> int:
    if jax.process_count() > 1:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def get_world_size() -> int:
    if jax.process_count() > 1:
        return jax.process_count()
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def is_initialized() -> bool:
    return _initialized


def init_parallel_env():
    """Reference: parallel.py init_parallel_env — rendezvous + process group
    bring-up. Here: jax.distributed.initialize when multi-host env vars are
    present (coordination service over DCN); single-host is a no-op.

    NOTE: must run before anything touches the XLA backend — so the env-var
    check comes first and no jax query (process_count/devices) happens
    before initialize."""
    global _initialized
    if _initialized:
        return
    coord = os.environ.get("PADDLE_MASTER") or \
        os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        port = os.environ.get("MASTER_PORT", coord.split(":")[-1]
                              if ":" in coord else "8476")
        try:
            already = jax.distributed.is_initialized()
        except AttributeError:   # older jax
            already = False
        if not already:
            jax.distributed.initialize(
                coordinator_address=f"{coord.split(':')[0]}:{port}",
                num_processes=nprocs, process_id=pid)
    # elastic liveness: auto-beat when the launcher asked for it
    try:
        from . import heartbeat as _hb
        _hb.start()
        # multi-host relay: rank 0 mirrors every rank's KV beats into
        # the primary controller's heartbeat dir so its file watcher
        # covers hosts with no shared filesystem
        relay_dir = os.environ.get("PADDLE_HEARTBEAT_KV_RELAY")
        if relay_dir and get_rank() == 0:
            _hb.start_kv_relay(relay_dir, range(get_world_size()))
    except Exception:
        pass
    _initialized = True


class ParallelEnv:
    """Reference: parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", "0"))

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank
