"""Distributed launch controller.

Reference capability: python/paddle/distributed/launch/main.py:21 (the
``python -m paddle.distributed.launch`` CLI) + controllers/collective.py:22
(CollectiveController: build per-rank envs, spawn, watch) + the failure
detection in controllers/watcher.py. TPU-native redesign: one process per
HOST (not per chip — XLA drives all local chips from one controller), with
rendezvous via jax.distributed's coordination service instead of TCPStore;
env-var names keep the PADDLE_* spelling so reference launch scripts port
unchanged.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle.distributed.launch parity CLI")
    p.add_argument("--nproc_per_node", "--nprocs", type=int, default=None,
                   help="processes on this node (default: 1; on TPU one "
                        "process drives all local chips)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", default=None,
                   help="coordinator host:port (defaults to 127.0.0.1 with "
                        "a free port for single-node runs)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--devices", default=None,
                   help="accepted for reference-CLI parity (XLA owns "
                        "device selection)")
    p.add_argument("--job_id", default="default")
    p.add_argument("script", help="training script to run")
    p.add_argument("--heartbeat_timeout", type=float, default=0.0,
                   help="seconds without a liveness beat before a worker "
                        "is declared dead (0 = off)")
    p.add_argument("--progress_timeout", type=float, default=0.0,
                   help="seconds without a training-progress beat before "
                        "an opted-in worker is declared wedged (0 = off)")
    p.add_argument("--peer_grace", type=float, default=None,
                   help="seconds survivors get to observe a dead peer's "
                        "tombstone and abort typed before the SIGTERM "
                        "sweep (default 4, env PADDLE_TPU_PEER_GRACE_S)")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _kill_all(procs, alive):
    """Terminate every still-alive worker: SIGTERM, a shared 10s grace
    window, then SIGKILL (both failure paths share this shutdown)."""
    for j in alive:
        procs[j].terminate()
    deadline = time.time() + 10
    for j in alive:
        try:
            procs[j].wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            procs[j].kill()
    alive.clear()


RESCALE_RC = 125   # controlled stop for an elastic re-scale (not a failure)
# Coordinated abort (collective.coordinated_abort): an INNOCENT rank
# exiting on a typed PeerLostError — a peer is CONFIRMED dead (marker).
# The elastic manager maps this to "peer failure — restart the world"
# and never treats the exiting rank as the sick one (no scale-in off
# its rc).
PEER_FAILURE_RC = 123
# Coordinated abort on a CollectiveTimeout: a contribution is MISSING
# but nothing confirmed the peer dead — it may be wedged-but-alive (a
# deterministic wedge would otherwise restart at full size forever),
# so this rc deliberately engages the manager's ordinary
# worker-failure path, scale-in heuristic included.
COLLECTIVE_TIMEOUT_RC = 122


def launch(script, script_args=(), nproc_per_node=1, nnodes=1, node_rank=0,
           master=None, log_dir=None, job_id="default",
           extra_env=None, heartbeat_timeout: float = 0.0,
           progress_timeout: float = 0.0, control_dir=None,
           peer_grace: float = None) -> int:
    """Spawn ``nproc_per_node`` worker processes with rendezvous env and
    watch them (reference: CollectiveController.run). Returns the exit
    code: 0 iff every worker exited 0; on any failure the remaining
    workers are terminated (the watcher's fail-fast).

    ``heartbeat_timeout``/``progress_timeout`` (seconds; 0 = off) enable
    the elastic liveness layer (distributed/heartbeat.py): workers beat
    per-rank files; a worker whose liveness beat goes stale — or whose
    training-progress beat goes stale after it opted in — is declared
    WEDGED and the job is killed (rc=124) so the elastic manager can
    restart it. This is the reference's etcd-heartbeat membership signal
    (fleet/elastic/manager.py:124) over the launcher's filesystem.

    Dead-peer tombstones (typed collective fault layer): every worker
    exit — crash or clean — writes a generation-keyed death marker into
    the heartbeat dir, which survivors' KV wait loops poll; a rank
    blocked in a collective on a dead peer raises ``PeerLostError``
    naming it within ~one poll interval. On the first worker failure
    the controller gives survivors a short ``peer_grace`` window
    (default 4s; env ``PADDLE_TPU_PEER_GRACE_S``) to observe the marker
    and exit with their typed error in their own logs before the
    SIGTERM sweep."""
    if master is None:
        master = f"127.0.0.1:{_free_port()}"
    host, port = master.rsplit(":", 1)
    world = nnodes * nproc_per_node
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    import tempfile
    # the heartbeat dir now always exists: it also carries the death
    # markers the typed collective fault layer polls (the staleness
    # WATCHER below still only runs when a timeout is configured)
    hb_tmp = None
    if log_dir:
        hb_dir = os.path.join(log_dir, "heartbeats")
    else:
        hb_dir = hb_tmp = tempfile.mkdtemp(prefix="paddle_hb_")
    os.makedirs(hb_dir, exist_ok=True)
    if peer_grace is None:
        try:
            peer_grace = float(
                os.environ.get("PADDLE_TPU_PEER_GRACE_S", "") or 4.0)
        except ValueError:
            peer_grace = 4.0
    # marker generation: elastic relaunches share a heartbeat dir, so
    # markers are keyed by the run index the manager exports to workers
    try:
        death_gen = int((extra_env or {}).get(
            "PADDLE_ELASTIC_RUN",
            os.environ.get("PADDLE_ELASTIC_RUN", "0")) or 0)
    except ValueError:
        death_gen = 0

    procs = []
    logs = []
    for local in range(nproc_per_node):
        rank = node_rank * nproc_per_node + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_RANK_IN_NODE": str(local),
            "PADDLE_LOCAL_SIZE": str(nproc_per_node),
            "PADDLE_MASTER": master,
            "MASTER_ADDR": host,
            "MASTER_PORT": port,
            "PADDLE_JOB_ID": str(job_id),
        })
        if hb_dir:
            env["PADDLE_HEARTBEAT_DIR"] = hb_dir
        env.update(extra_env or {})
        if log_dir:
            log = open(os.path.join(log_dir, f"workerlog.{rank}"), "wb")
            out = err = log
        else:
            log = None
            out = err = None
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", script, *script_args], env=env,
            stdout=out, stderr=err))

    rc = 0
    job_start = time.time()
    try:
        from .. import heartbeat as _hb
        # a relaunch into the same log_dir must not inherit stale
        # markers: older generations, this node's own ranks, and
        # pre-start abort markers are swept; other nodes' live
        # same-generation tombstones survive
        _hb.clear_run_markers(
            hb_dir, generation=death_gen,
            own_ranks=[node_rank * nproc_per_node + l
                       for l in range(nproc_per_node)])
        alive = set(range(len(procs)))
        rescale_flag = os.path.join(control_dir, "rescale") \
            if control_dir else None

        def _tombstone(local, r):
            # job-scoped (master addr): a later job reusing this
            # log_dir at the same generation must never honor these
            _hb.mark_dead(node_rank * nproc_per_node + local,
                          f"worker exited rc={r}", dir_path=hb_dir,
                          generation=death_gen, job=master)

        while alive:
            time.sleep(0.2)
            # poll exits BEFORE honoring a rescale flag: a world whose
            # workers all just finished must report success, not be
            # relaunched because capacity grew in the same instant
            for i in list(alive):
                r = procs[i].poll()
                if r is None:
                    continue
                alive.discard(i)
                # death marker on EVERY exit: a rank that left — even
                # cleanly — can never contribute to a survivor's pending
                # collective, so survivors should fail fast and typed
                # instead of waiting out the deadline
                _tombstone(i, r)
                if r != 0:
                    # fail fast: one dead worker kills the job
                    # (reference: watcher peer-failure propagation) —
                    # but first give survivors a grace window to observe
                    # the tombstone and exit with their typed
                    # PeerLostError in their own logs. rc stays the
                    # PRIMARY failure's; secondary exits during the
                    # grace are reaped and tombstoned only.
                    rc = r
                    print(f"[launch] rank "
                          f"{node_rank * nproc_per_node + i} failed "
                          f"(rc={r}); tombstoned, giving peers "
                          f"{peer_grace:.1f}s to abort typed",
                          file=sys.stderr)
                    deadline = time.time() + max(peer_grace, 0.0)
                    while alive and time.time() < deadline:
                        for j in list(alive):
                            rj = procs[j].poll()
                            if rj is not None:
                                alive.discard(j)
                                _tombstone(j, rj)
                        time.sleep(0.05)
                    _kill_all(procs, alive)
                    break
            if not alive:
                break
            if rescale_flag and os.path.exists(rescale_flag):
                # elastic re-scale request (fleet/elastic.py): stop the
                # world cleanly so the manager can relaunch at the new
                # size; workers resume from their latest checkpoint
                print("[launch] re-scale requested; stopping world for "
                      "elastic relaunch", file=sys.stderr)
                rc = RESCALE_RC
                _kill_all(procs, alive)
                break
            if hb_dir and (heartbeat_timeout > 0 or progress_timeout > 0):
                my_ranks = [node_rank * nproc_per_node + l
                            for l in range(nproc_per_node)]
                stale = _hb.check_stale(
                    hb_dir, my_ranks,
                    auto_timeout=heartbeat_timeout,
                    progress_timeout=progress_timeout,
                    started_at=job_start)
                stale = {r - node_rank * nproc_per_node: why
                         for r, why in stale.items()}
                stale = {r: why for r, why in stale.items() if r in alive}
                if stale:
                    for r, why in stale.items():
                        print(f"[launch] rank {r} wedged: {why}; "
                              "killing job for elastic restart",
                              file=sys.stderr)
                        # tombstone the wedged rank too: peers of a
                        # multi-NODE job (other controllers' workers)
                        # see the marker through shared storage
                        _hb.mark_dead(node_rank * nproc_per_node + r,
                                      f"wedged: {why}", dir_path=hb_dir,
                                      generation=death_gen, job=master)
                    rc = 124
                    _kill_all(procs, alive)
                    break
    except KeyboardInterrupt:
        for pr in procs:
            pr.send_signal(signal.SIGTERM)
        rc = 130
    finally:
        for log in logs:
            if log:
                log.close()
        if hb_tmp is not None:
            # launcher-owned temp heartbeat dir: every worker is dead by
            # now, so the beats/markers have no remaining reader — an
            # elastic manager churning restarts must not leak one temp
            # dir per attempt
            import shutil
            shutil.rmtree(hb_tmp, ignore_errors=True)
    return rc


def main(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    nproc = args.nproc_per_node or 1
    rc = launch(args.script, args.script_args, nproc_per_node=nproc,
                nnodes=args.nnodes, node_rank=args.node_rank,
                master=args.master, log_dir=args.log_dir,
                job_id=args.job_id,
                heartbeat_timeout=args.heartbeat_timeout,
                progress_timeout=args.progress_timeout,
                peer_grace=args.peer_grace)
    sys.exit(rc)


if __name__ == "__main__":
    main()
