"""Distributed launch controller.

Reference capability: python/paddle/distributed/launch/main.py:21 (the
``python -m paddle.distributed.launch`` CLI) + controllers/collective.py:22
(CollectiveController: build per-rank envs, spawn, watch) + the failure
detection in controllers/watcher.py. TPU-native redesign: one process per
HOST (not per chip — XLA drives all local chips from one controller), with
rendezvous via jax.distributed's coordination service instead of TCPStore;
env-var names keep the PADDLE_* spelling so reference launch scripts port
unchanged.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle.distributed.launch parity CLI")
    p.add_argument("--nproc_per_node", "--nprocs", type=int, default=None,
                   help="processes on this node (default: 1; on TPU one "
                        "process drives all local chips)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", default=None,
                   help="coordinator host:port (defaults to 127.0.0.1 with "
                        "a free port for single-node runs)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--devices", default=None,
                   help="accepted for reference-CLI parity (XLA owns "
                        "device selection)")
    p.add_argument("--job_id", default="default")
    p.add_argument("script", help="training script to run")
    p.add_argument("--heartbeat_timeout", type=float, default=0.0,
                   help="seconds without a liveness beat before a worker "
                        "is declared dead (0 = off)")
    p.add_argument("--progress_timeout", type=float, default=0.0,
                   help="seconds without a training-progress beat before "
                        "an opted-in worker is declared wedged (0 = off)")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _kill_all(procs, alive):
    """Terminate every still-alive worker: SIGTERM, a shared 10s grace
    window, then SIGKILL (both failure paths share this shutdown)."""
    for j in alive:
        procs[j].terminate()
    deadline = time.time() + 10
    for j in alive:
        try:
            procs[j].wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            procs[j].kill()
    alive.clear()


RESCALE_RC = 125   # controlled stop for an elastic re-scale (not a failure)


def launch(script, script_args=(), nproc_per_node=1, nnodes=1, node_rank=0,
           master=None, log_dir=None, job_id="default",
           extra_env=None, heartbeat_timeout: float = 0.0,
           progress_timeout: float = 0.0, control_dir=None) -> int:
    """Spawn ``nproc_per_node`` worker processes with rendezvous env and
    watch them (reference: CollectiveController.run). Returns the exit
    code: 0 iff every worker exited 0; on any failure the remaining
    workers are terminated (the watcher's fail-fast).

    ``heartbeat_timeout``/``progress_timeout`` (seconds; 0 = off) enable
    the elastic liveness layer (distributed/heartbeat.py): workers beat
    per-rank files; a worker whose liveness beat goes stale — or whose
    training-progress beat goes stale after it opted in — is declared
    WEDGED and the job is killed (rc=124) so the elastic manager can
    restart it. This is the reference's etcd-heartbeat membership signal
    (fleet/elastic/manager.py:124) over the launcher's filesystem."""
    if master is None:
        master = f"127.0.0.1:{_free_port()}"
    host, port = master.rsplit(":", 1)
    world = nnodes * nproc_per_node
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    hb_dir = None
    if heartbeat_timeout > 0 or progress_timeout > 0:
        import tempfile
        hb_dir = os.path.join(log_dir, "heartbeats") if log_dir             else tempfile.mkdtemp(prefix="paddle_hb_")
        os.makedirs(hb_dir, exist_ok=True)

    procs = []
    logs = []
    for local in range(nproc_per_node):
        rank = node_rank * nproc_per_node + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_RANK_IN_NODE": str(local),
            "PADDLE_LOCAL_SIZE": str(nproc_per_node),
            "PADDLE_MASTER": master,
            "MASTER_ADDR": host,
            "MASTER_PORT": port,
            "PADDLE_JOB_ID": str(job_id),
        })
        if hb_dir:
            env["PADDLE_HEARTBEAT_DIR"] = hb_dir
        env.update(extra_env or {})
        if log_dir:
            log = open(os.path.join(log_dir, f"workerlog.{rank}"), "wb")
            out = err = log
        else:
            log = None
            out = err = None
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", script, *script_args], env=env,
            stdout=out, stderr=err))

    rc = 0
    job_start = time.time()
    try:
        from .. import heartbeat as _hb
        alive = set(range(len(procs)))
        rescale_flag = os.path.join(control_dir, "rescale") \
            if control_dir else None
        while alive:
            time.sleep(0.2)
            # poll exits BEFORE honoring a rescale flag: a world whose
            # workers all just finished must report success, not be
            # relaunched because capacity grew in the same instant
            for i in list(alive):
                r = procs[i].poll()
                if r is None:
                    continue
                alive.discard(i)
                if r != 0:
                    # fail fast: one dead worker kills the job
                    # (reference: watcher peer-failure propagation).
                    # Break immediately: continuing over the pre-kill
                    # snapshot would poll the peers _kill_all just
                    # SIGTERMed and overwrite rc with their -15
                    rc = r
                    _kill_all(procs, alive)
                    break
            if not alive:
                break
            if rescale_flag and os.path.exists(rescale_flag):
                # elastic re-scale request (fleet/elastic.py): stop the
                # world cleanly so the manager can relaunch at the new
                # size; workers resume from their latest checkpoint
                print("[launch] re-scale requested; stopping world for "
                      "elastic relaunch", file=sys.stderr)
                rc = RESCALE_RC
                _kill_all(procs, alive)
                break
            if hb_dir:
                my_ranks = [node_rank * nproc_per_node + l
                            for l in range(nproc_per_node)]
                stale = _hb.check_stale(
                    hb_dir, my_ranks,
                    auto_timeout=heartbeat_timeout,
                    progress_timeout=progress_timeout,
                    started_at=job_start)
                stale = {r - node_rank * nproc_per_node: why
                         for r, why in stale.items()}
                stale = {r: why for r, why in stale.items() if r in alive}
                if stale:
                    for r, why in stale.items():
                        print(f"[launch] rank {r} wedged: {why}; "
                              "killing job for elastic restart",
                              file=sys.stderr)
                    rc = 124
                    _kill_all(procs, alive)
                    break
    except KeyboardInterrupt:
        for pr in procs:
            pr.send_signal(signal.SIGTERM)
        rc = 130
    finally:
        for log in logs:
            if log:
                log.close()
    return rc


def main(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    nproc = args.nproc_per_node or 1
    rc = launch(args.script, args.script_args, nproc_per_node=nproc,
                nnodes=args.nnodes, node_rank=args.node_rank,
                master=args.master, log_dir=args.log_dir,
                job_id=args.job_id,
                heartbeat_timeout=args.heartbeat_timeout,
                progress_timeout=args.progress_timeout)
    sys.exit(rc)


if __name__ == "__main__":
    main()
