"""paddle.distributed.launch parity package (reference:
python/paddle/distributed/launch/__init__.py)."""
from .main import launch, main  # noqa
