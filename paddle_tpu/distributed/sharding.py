"""paddle.distributed.sharding — the group-sharded (ZeRO) user API.

Reference capability: python/paddle/distributed/sharding/
{group_sharded.py group_sharded_parallel, save_group_sharded_model} —
wrap (model, optimizer, scaler) so parameters/grads/optimizer state are
sharded across the data-parallel group at ZeRO stage os (1) / os_g (2) /
p_g_os (3).

TPU-native design: the stages map onto the GSPMD sharding machinery in
distributed.api (ShardingStage1/2/3 + shard_optimizer) — XLA inserts the
reduce-scatter/all-gather the reference's hand-written stage hooks do
manually. The memory evidence per stage is tested in
tests/test_zero_stages.py.
"""
from __future__ import annotations

import os

from ..core import enforce as E
from .api import (ShardingStage1, ShardingStage2, ShardingStage3,
                  shard_optimizer)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]

_LEVELS = {"os": ShardingStage1, "os_g": ShardingStage2,
           "p_g_os": ShardingStage3}


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Shard ``optimizer`` state (and, for p_g_os, parameters) over the
    data-parallel axis. Returns (model, optimizer, scaler) like the
    reference (group_sharded.py:33). ``offload`` (CPU moments) is not
    supported on the jit path and raises; the buffer/segment knobs are
    accepted for parity — XLA owns comm bucketing (recorded in
    docs/CAPABILITY_DELTA.md).
    """
    E.enforce(level in _LEVELS,
              f"level must be one of {sorted(_LEVELS)} (ZeRO 1/2/3), "
              f"got {level!r}", E.InvalidArgumentError)
    if offload:
        raise E.UnimplementedError(
            "offload=True (CPU-placed moments) is not supported",
            hint="jitted updates require device-resident optimizer state")
    stage = _LEVELS[level]()
    optimizer = shard_optimizer(optimizer, stage)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Gather the sharded model (and optimizer state) and save it under
    ``output`` as a single-rank checkpoint (reference:
    group_sharded.py:151 — output must be a directory)."""
    from .. import save
    from . import get_rank

    if os.path.splitext(output)[1]:
        raise E.InvalidArgumentError(
            f"save_group_sharded_model expects a directory, got {output!r}")
    os.makedirs(output, exist_ok=True)
    if get_rank() == 0:
        save(model.state_dict(), os.path.join(output, "model.pdmodel"))
        if optimizer is not None:
            inner = getattr(optimizer, "_inner_opt", None) or \
                getattr(optimizer, "_optimizer", optimizer)
            state = getattr(inner, "state_dict", dict)()
            save(state, os.path.join(output, "model.pdopt"))
