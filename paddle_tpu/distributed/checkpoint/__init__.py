"""Distributed checkpoint: save/load with reshard-on-load and an
atomic commit protocol.

Reference: python/paddle/distributed/checkpoint/{save_state_dict.py:104,
load_state_dict.py:377, metadata.py} — per-rank shard files + a global
Metadata of tensor→shard mapping; load re-shards across different
meshes/strategies.

TPU-native design: a sharded jax.Array knows its own layout, so the save
path walks addressable shards (each host writes only what it owns — the
per-rank shard files of the reference) and the metadata records the global
shape plus each shard's index window. Load assembles requested windows and
``device_put``s onto the *target* tensor's sharding — reshard-on-load for
free, including across different meshes.

Crash consistency (Orbax-style commit protocol): every save stages into
``<path>.tmp.<uid>`` — shard files, then the metadata, then a
``checkpoint.manifest`` recording every file's size + CRC32 — and only
after every host has finished writing does the coordinator rename the
staging dir to ``<path>`` and drop a ``COMMIT`` marker. A ``kill -9`` at
any instant therefore leaves either (a) a stale staging dir and the
previous checkpoint untouched, (b) a fully-renamed dir missing only
its COMMIT marker, or — only when overwriting an existing non-empty
``path`` in place, which the manager's one-dir-per-step layout never
does — (c) the previous checkpoint moved aside to ``<path>.old.<uid>``
(raised failures move it back; CheckpointManager recovers graveyards
left by kills). :func:`load_state_dict` refuses anything uncommitted
or checksum-corrupt with an error that names the file.
:mod:`paddle_tpu.testing.faults` points (``checkpoint.write`` /
``checkpoint.metadata`` / ``checkpoint.rename`` / ``checkpoint.commit``)
let tests kill the process at each stage; the crash-consistency suite
holds the protocol to that contract.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
import zlib
from typing import Dict, Optional

import jax
import numpy as np

from ...core.tensor import Tensor
from ...testing import faults as _faults
from ... import monitor as _monitor

__all__ = ["save_state_dict", "load_state_dict", "CheckpointError",
           "is_committed", "verify_checkpoint"]

_META_NAME = "0.metadata"
_MANIFEST_NAME = "checkpoint.manifest"
_COMMIT_NAME = "COMMIT"
_FORMAT = "paddle_tpu_dckpt_v2"

# process-local staging-uid sequence (multi-save-per-process uniqueness;
# cross-process uniqueness comes from the pid component)
_UID_SEQ = [0]
# per-path save-attempt counts: every host saves the same paths in the
# same order (failures propagate to all hosts through the status
# gathers), so this yields host-identical collective tags even from the
# async writer thread — see all_gather_object's tag contract
_SAVE_ATTEMPTS: Dict[str, int] = {}

# Tagged-gather KV reclamation. The coordination-service KV store never
# frees keys on its own, and checkpointing makes tagged exchanges the
# dominant producer (3 per save, one carrying full metadata), so each
# process deletes ITS OWN keys once they are provably read: within one
# STREAM (one checkpoint root) multi-host ops run in lockstep program
# order on every host, so when this process starts the stream's op G,
# every peer has finished reading op G-1's keys (it had to, to produce
# the op-(G-1) keys this process already consumed) — the stream's keys
# from ops <= G-2 are therefore dead. Generations are tracked per
# stream and mutated under a lock: two live managers (two roots) save
# from their own async writer threads concurrently.
_TAG_MU = threading.Lock()
_TAG_GENS: Dict[str, int] = {}
_SPENT_KEYS: list = []      # (stream, generation, kv key this process wrote)


def _begin_tagged_op_and_reclaim(stream: str) -> int:
    """Open a new tagged-exchange generation for ``stream``; delete this
    process's KV keys from that stream's generations at least two back.
    Returns the generation."""
    with _TAG_MU:
        gen = _TAG_GENS.get(stream, 0) + 1
        _TAG_GENS[stream] = gen
        doomed = [k for s, g, k in _SPENT_KEYS
                  if s == stream and g <= gen - 2]
        _SPENT_KEYS[:] = [e for e in _SPENT_KEYS
                          if not (e[0] == stream and e[1] <= gen - 2)]
    if doomed:
        from ..collective import _coord_client
        client = _coord_client()
        if client is not None:
            for key in doomed:
                try:
                    client.key_value_delete(key)
                except Exception:
                    pass
    return gen


def _note_tagged_key(stream: str, tag: str):
    """Record the KV key this process wrote for a tagged gather, for
    later reclamation."""
    from .. import env as _env
    with _TAG_MU:
        _SPENT_KEYS.append((stream, _TAG_GENS.get(stream, 0),
                            f"ag_{tag}_{_env.get_rank()}"))


class CheckpointError(RuntimeError):
    """A checkpoint directory is unusable: uncommitted (interrupted
    save) or corrupt (manifest checksum/size mismatch). The message
    names the directory and the offending file."""


def _flat_items(state_dict, prefix=""):
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            yield from _flat_items(v, prefix=f"{key}.")
        else:
            yield key, v


def _local_uid() -> str:
    _UID_SEQ[0] += 1
    return f"{os.getpid()}.{_UID_SEQ[0]}"


def _crc32_of(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


def _atomic_write_json(payload: dict, dest: str):
    tmp = f"{dest}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dest)


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id: Optional[int] = None):
    """Reference: save_state_dict.py:104. Each host writes its addressable
    shards; the coordinator writes the metadata + manifest, renames the
    staging dir into place, and drops the COMMIT marker (atomic commit —
    see the module docstring). Returns only after the commit is visible
    on every host."""
    t0 = time.perf_counter()
    try:
        _save_committed(state_dict, path, process_group,
                        coordinator_rank, unique_id)
    except BaseException:
        _monitor.inc("ckpt.commit.failures",
                     doc="checkpoint saves that failed before COMMIT")
        raise
    _monitor.inc("ckpt.saves", doc="committed checkpoint saves")
    _monitor.observe("ckpt.save.duration_ms",
                     (time.perf_counter() - t0) * 1e3,
                     doc="wall time of one committed save (ms)")


def _save_committed(state_dict, path, process_group, coordinator_rank,
                    unique_id):
    path = os.path.normpath(path)
    multi = jax.process_count() > 1
    pid = jax.process_index()
    uid = str(unique_id) if unique_id is not None else _local_uid()
    tag_base = None
    if multi:
        # every host must stage into the SAME directory: adopt the
        # coordinator's uid proposal
        from .. import collective as _coll
        stream = os.path.dirname(path) or path
        with _TAG_MU:
            _SAVE_ATTEMPTS[path] = _SAVE_ATTEMPTS.get(path, 0) + 1
            attempt = _SAVE_ATTEMPTS[path]
        tag_base = f"dckpt{zlib.crc32(path.encode()):08x}a{attempt}"
        _begin_tagged_op_and_reclaim(stream)
        proposals: list = []
        _coll.all_gather_object(proposals, uid, tag=f"{tag_base}.uid")
        _note_tagged_key(stream, f"{tag_base}.uid")
        uid = proposals[coordinator_rank]
    staging = f"{path}.tmp.{uid}"
    os.makedirs(staging, exist_ok=True)

    # -- phase 1: every host writes its own shards into the staging dir.
    # A raised local failure must still reach the metadata gather below
    # (or the peers would block a full KV timeout on a missing
    # contribution and then mis-pair later gathers), so it is carried as
    # a status payload instead of propagating immediately.
    local_err: Optional[BaseException] = None
    meta: dict = {}
    files: dict = {}
    try:
        meta, files = _write_local_shards(state_dict, staging, pid)
    except BaseException as e:
        if not multi:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        local_err = e
    if multi:
        # Multi-host: every host contributes its shard windows + file
        # stats (or its write error); the coordinator merges before
        # writing (the reference's global Metadata of tensor->shard
        # mapping, metadata.py). Exchange rides the coordination-service
        # KV store; the gather doubles as the write barrier — once it
        # returns, every host's shard file is fully on disk.
        from .. import collective as _coll
        all_metas: list = []
        _coll.all_gather_object(all_metas, {
            "meta": meta, "files": files,
            "error": repr(local_err) if local_err is not None else None},
            tag=f"{tag_base}.meta")
        _note_tagged_key(stream, f"{tag_base}.meta")
        peer_errs = [p["error"] for p in all_metas if p["error"]]
        if local_err is not None or peer_errs:
            if pid == coordinator_rank:
                shutil.rmtree(staging, ignore_errors=True)
            if local_err is not None:
                raise local_err
            raise CheckpointError(
                f"checkpoint save to {path!r} aborted: a peer host "
                f"failed writing its shards ({peer_errs[0]})")
        if pid == coordinator_rank:
            merged = {"tensors": {}, "format": meta["format"]}
            for payload in all_metas:
                files.update(payload["files"])
                for key, entry in payload["meta"]["tensors"].items():
                    if entry.get("kind") == "object":
                        merged["tensors"].setdefault(key, entry)
                        continue
                    tgt = merged["tensors"].setdefault(
                        key, {**entry, "shards": []})
                    windows = {tuple(map(tuple, s["window"]))
                               for s in tgt["shards"]}
                    for s in entry["shards"]:
                        if tuple(map(tuple, s["window"])) not in windows:
                            tgt["shards"].append(s)
            meta = merged

    # -- phase 2: the coordinator writes metadata + manifest, renames
    # the staging dir into place, and drops the COMMIT marker. Its
    # outcome is broadcast in phase 3, so a commit failure surfaces on
    # every host instead of as a bare barrier timeout.
    commit_err: Optional[BaseException] = None
    if pid == coordinator_rank:
        graveyard = None
        try:
            _faults.hit("checkpoint.metadata")
            meta_path = os.path.join(staging, _META_NAME)
            _atomic_write_json(meta, meta_path)
            files[_META_NAME] = {"size": os.path.getsize(meta_path),
                                 "crc32": _crc32_of(meta_path)}
            _atomic_write_json(
                {"format": _FORMAT, "uid": uid, "files": files},
                os.path.join(staging, _MANIFEST_NAME))
            _faults.hit("checkpoint.rename")
            if os.path.exists(path):
                if os.listdir(path):
                    # overwrite of a live directory: move it aside first
                    # (rename(2) cannot replace a non-empty dir). A kill
                    # inside this window strands the old checkpoint at
                    # <path>.old.<uid>; CheckpointManager recovers such
                    # graveyards, and the manager's normal layout (a
                    # fresh dir per step) never takes this branch.
                    graveyard = f"{path}.old.{uid}"
                    os.rename(path, graveyard)
                else:
                    os.rmdir(path)
            os.rename(staging, path)
            _faults.hit("checkpoint.commit")
            _atomic_write_json({"uid": uid, "ts": time.time()},
                               os.path.join(path, _COMMIT_NAME))
            if graveyard is not None:
                shutil.rmtree(graveyard, ignore_errors=True)
        except BaseException as e:
            commit_err = e
            _restore_graveyard(path, graveyard)
            shutil.rmtree(staging, ignore_errors=True)
    if multi:
        # phase 3: commit-status exchange — doubles as the return
        # barrier (no host returns — or exits — before the commit
        # landed; each gather uses a fresh KV key, so a failed round
        # can't pair with a later save's)
        from .. import collective as _coll
        statuses: list = []
        _coll.all_gather_object(
            statuses, repr(commit_err) if commit_err is not None else None,
            tag=f"{tag_base}.status")
        _note_tagged_key(stream, f"{tag_base}.status")
        if commit_err is not None:
            raise commit_err
        bad = [s for s in statuses if s]
        if bad:
            raise CheckpointError(
                f"checkpoint save to {path!r} aborted: the coordinator "
                f"failed to commit ({bad[0]})")
    elif commit_err is not None:
        raise commit_err


def _write_local_shards(state_dict, staging: str, pid: int):
    """Phase 1 of the commit protocol: write this host's shard file into
    the staging dir; returns (local metadata, {fname: {size, crc32}})."""
    meta = {"tensors": {}, "format": _FORMAT}
    shard_file = os.path.join(staging, f"{pid}_0.distcp")
    blobs = {}
    for key, v in _flat_items(state_dict):
        if isinstance(v, Tensor):
            arr = v._data
        elif isinstance(v, (jax.Array, np.ndarray)):
            arr = v
        else:
            meta["tensors"][key] = {"kind": "object", "value": v}
            continue
        arr = jax.device_put(arr) if not isinstance(arr, jax.Array) else arr
        entry = {"kind": "tensor", "global_shape": list(arr.shape),
                 "dtype": str(arr.dtype), "shards": []}
        seen = set()
        for shard in arr.addressable_shards:
            window = tuple(
                (s.start or 0,
                 s.stop if s.stop is not None else dim)
                for s, dim in zip(shard.index, arr.shape))
            if window in seen:
                continue  # replicated copies: write once
            seen.add(window)
            blob_key = f"{key}@{len(entry['shards'])}"
            blobs[blob_key] = np.asarray(shard.data)
            entry["shards"].append(
                {"window": [list(w) for w in window],
                 "file": os.path.basename(shard_file), "key": blob_key})
        meta["tensors"][key] = entry
    _faults.hit("checkpoint.write")
    np.savez(shard_file, **blobs)
    # np.savez appends .npz — normalize name.
    if os.path.exists(shard_file + ".npz"):
        os.replace(shard_file + ".npz", shard_file)
    files = {os.path.basename(shard_file): {
        "size": os.path.getsize(shard_file),
        "crc32": _crc32_of(shard_file)}}
    _monitor.inc("ckpt.save.bytes",
                 files[os.path.basename(shard_file)]["size"],
                 doc="shard bytes written by committed+failed saves")
    return meta, files


def _restore_graveyard(path: str, graveyard: Optional[str]):
    """Undo a move-aside after a raised commit failure: put the
    previously-committed checkpoint back at ``path`` (dropping an
    uncommitted half-renamed staging dir if one landed there)."""
    if graveyard is None or not os.path.exists(graveyard):
        return
    try:
        if os.path.exists(path):
            if os.path.isfile(os.path.join(path, _COMMIT_NAME)):
                return          # a committed checkpoint won; keep it
            shutil.rmtree(path, ignore_errors=True)
        os.rename(graveyard, path)
    except OSError:
        pass


def is_committed(path: str) -> bool:
    """True when ``path`` holds a fully-committed checkpoint (COMMIT
    marker + manifest + metadata present)."""
    return (os.path.isfile(os.path.join(path, _COMMIT_NAME))
            and os.path.isfile(os.path.join(path, _MANIFEST_NAME))
            and os.path.isfile(os.path.join(path, _META_NAME)))


def verify_checkpoint(path: str):
    """Raise :class:`CheckpointError` unless ``path`` is committed and
    every manifest file matches its recorded size and CRC32."""
    if not os.path.isdir(path):
        raise CheckpointError(f"checkpoint dir {path!r} does not exist")
    if not os.path.isfile(os.path.join(path, _COMMIT_NAME)):
        raise CheckpointError(
            f"checkpoint {path!r} has no COMMIT marker — the save was "
            "interrupted before commit; refusing to load a partial "
            "checkpoint (restore from the previous committed one)")
    manifest_path = os.path.join(path, _MANIFEST_NAME)
    if not os.path.isfile(manifest_path):
        raise CheckpointError(
            f"checkpoint {path!r} is committed but has no manifest — "
            "cannot verify integrity")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(
            f"checkpoint {path!r}: unreadable manifest: {e}") from e
    for fname, rec in manifest.get("files", {}).items():
        fpath = os.path.join(path, fname)
        if not os.path.isfile(fpath):
            raise CheckpointError(
                f"checkpoint {path!r}: file {fname!r} listed in the "
                "manifest is missing")
        size = os.path.getsize(fpath)
        if size != rec["size"]:
            raise CheckpointError(
                f"checkpoint {path!r}: file {fname!r} is {size} bytes, "
                f"manifest says {rec['size']} — truncated or overwritten")
        crc = _crc32_of(fpath)
        if crc != rec["crc32"]:
            raise CheckpointError(
                f"checkpoint {path!r}: file {fname!r} fails its CRC32 "
                f"check ({crc:#010x} != manifest {rec['crc32']:#010x}) "
                "— corrupt")


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id: Optional[int] = None,
                    offload: bool = False, verify: bool = True):
    """Reference: load_state_dict.py:377 — fills ``state_dict`` in place,
    resharding saved shards onto each target tensor's current sharding.

    ``verify=True`` (default) enforces the commit protocol: an
    uncommitted or checksum-failing directory raises
    :class:`CheckpointError` instead of half-loading. The CRC pass costs
    one extra sequential read of the checkpoint before the load — paid
    only on restores, which are rare and correctness-critical. Pass
    ``verify=False`` to skip it (and to read pre-protocol v1 dirs)."""
    if verify:
        verify_checkpoint(path)
    meta_path = os.path.join(path, _META_NAME)
    if not os.path.isfile(meta_path):
        raise CheckpointError(
            f"checkpoint {path!r} has no {_META_NAME} — not a "
            "checkpoint directory")
    with open(meta_path) as f:
        meta = json.load(f)
    files = {}

    def _file(fname):
        if fname not in files:
            files[fname] = np.load(os.path.join(path, fname))
        return files[fname]

    def _assemble(entry) -> np.ndarray:
        full = np.zeros(entry["global_shape"],
                        dtype=np.dtype(entry["dtype"]))
        for sh in entry["shards"]:
            idx = tuple(slice(a, b) for a, b in sh["window"])
            full[idx] = _file(sh["file"])[sh["key"]]
        return full

    def _fill(d, prefix=""):
        for k, v in d.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                _fill(v, prefix=f"{key}.")
                continue
            entry = meta["tensors"].get(key)
            if entry is None:
                continue
            if entry.get("kind") == "object":
                d[k] = entry["value"]
                continue
            full = _assemble(entry)
            if isinstance(v, Tensor):
                tgt = v._data
                sharding = getattr(tgt, "sharding", None)
                arr = jax.device_put(full.astype(tgt.dtype), sharding) \
                    if sharding is not None else jax.numpy.asarray(full)
                v._data = arr
            else:
                d[k] = jax.numpy.asarray(full)

    _fill(state_dict)


class AsyncSaveHandle:
    """Handle for an in-flight async checkpoint (reference capability:
    async save in the checkpoint subsystem — VERDICT r2 recorded the
    sync-only delta). ``result()`` joins and re-raises any writer
    error."""

    def __init__(self, thread, errbox):
        self._thread = thread
        self._err = errbox

    def done(self) -> bool:
        return not self._thread.is_alive()

    def wait(self, timeout=None):
        self._thread.join(timeout)

    def result(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"async checkpoint still writing after {timeout}s")
        if self._err:
            raise self._err[0]


def async_save_state_dict(state_dict: Dict, path: str, process_group=None,
                          coordinator_rank: int = 0,
                          unique_id: Optional[int] = None) -> AsyncSaveHandle:
    """Checkpoint without blocking training: the device->host snapshot
    happens now (so the caller may mutate parameters immediately after
    return); file IO, the metadata merge, and the atomic commit run on a
    background thread.

    TPU-native note: the snapshot is the unavoidable synchronous cost
    (HBM->host copy); overlapping the *disk* write is where the win is —
    same structure as the reference's async save worker."""
    import threading

    # snapshot phase (synchronous): host copies of every shard
    snapshot: Dict = {}
    for key, v in _flat_items(state_dict):
        if isinstance(v, Tensor):
            arr = v._data
        elif isinstance(v, (jax.Array, np.ndarray)):
            arr = v
        else:
            snapshot[key] = v
            continue
        if isinstance(arr, jax.Array) and not isinstance(arr, np.ndarray):
            # device-side copy with the SAME sharding: decouples the
            # snapshot from the caller's buffers (donation/mutation of
            # the original cannot touch this copy), while the writer
            # still sees per-shard windows
            import jax.numpy as jnp

            snapshot[key] = jax.block_until_ready(jnp.copy(arr))
        else:
            snapshot[key] = np.asarray(arr)

    errbox: list = []

    def writer():
        try:
            save_state_dict(snapshot, path, process_group,
                            coordinator_rank, unique_id)
        except BaseException as e:   # surfaced via result()
            errbox.append(e)

    th = threading.Thread(target=writer, daemon=True,
                          name="dckpt-async-save")
    th.start()
    return AsyncSaveHandle(th, errbox)


__all__ += ["async_save_state_dict", "AsyncSaveHandle"]

from .manager import CheckpointManager  # noqa: E402

__all__ += ["CheckpointManager"]
