"""Distributed checkpoint: save/load with reshard-on-load.

Reference: python/paddle/distributed/checkpoint/{save_state_dict.py:104,
load_state_dict.py:377, metadata.py} — per-rank shard files + a global
Metadata of tensor→shard mapping; load re-shards across different
meshes/strategies.

TPU-native design: a sharded jax.Array knows its own layout, so the save
path walks addressable shards (each host writes only what it owns — the
per-rank shard files of the reference) and the metadata records the global
shape plus each shard's index window. Load assembles requested windows and
``device_put``s onto the *target* tensor's sharding — reshard-on-load for
free, including across different meshes. Orbax is the production-grade
equivalent; this implementation keeps the reference's on-disk model
(metadata + shard files) explicit and dependency-light.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import numpy as np

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]

_META_NAME = "0.metadata"


def _flat_items(state_dict, prefix=""):
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            yield from _flat_items(v, prefix=f"{key}.")
        else:
            yield key, v


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id: Optional[int] = None):
    """Reference: save_state_dict.py:104. Each host writes its addressable
    shards; coordinator writes the metadata."""
    os.makedirs(path, exist_ok=True)
    pid = jax.process_index()
    meta = {"tensors": {}, "format": "paddle_tpu_dckpt_v1"}
    shard_file = os.path.join(path, f"{pid}_0.distcp")
    blobs = {}
    for key, v in _flat_items(state_dict):
        if isinstance(v, Tensor):
            arr = v._data
        elif isinstance(v, (jax.Array, np.ndarray)):
            arr = v
        else:
            meta["tensors"][key] = {"kind": "object", "value": v}
            continue
        arr = jax.device_put(arr) if not isinstance(arr, jax.Array) else arr
        entry = {"kind": "tensor", "global_shape": list(arr.shape),
                 "dtype": str(arr.dtype), "shards": []}
        seen = set()
        for shard in arr.addressable_shards:
            window = tuple(
                (s.start or 0,
                 s.stop if s.stop is not None else dim)
                for s, dim in zip(shard.index, arr.shape))
            if window in seen:
                continue  # replicated copies: write once
            seen.add(window)
            blob_key = f"{key}@{len(entry['shards'])}"
            blobs[blob_key] = np.asarray(shard.data)
            entry["shards"].append(
                {"window": [list(w) for w in window],
                 "file": os.path.basename(shard_file), "key": blob_key})
        meta["tensors"][key] = entry
    np.savez(shard_file, **blobs)
    # np.savez appends .npz — normalize name.
    if os.path.exists(shard_file + ".npz"):
        os.replace(shard_file + ".npz", shard_file)
    if jax.process_count() > 1:
        # Multi-host: every host contributes its shard windows; the
        # coordinator merges before writing (the reference's global Metadata
        # of tensor->shard mapping, metadata.py). Exchange rides the
        # coordination-service KV store (collective.all_gather_object).
        from .. import collective as _coll
        all_metas: list = []
        _coll.all_gather_object(all_metas, meta)
        if jax.process_index() == coordinator_rank:
            merged = {"tensors": {}, "format": meta["format"]}
            for m in all_metas:
                for key, entry in m["tensors"].items():
                    if entry.get("kind") == "object":
                        merged["tensors"].setdefault(key, entry)
                        continue
                    tgt = merged["tensors"].setdefault(
                        key, {**entry, "shards": []})
                    windows = {tuple(map(tuple, s["window"]))
                               for s in tgt["shards"]}
                    for s in entry["shards"]:
                        if tuple(map(tuple, s["window"])) not in windows:
                            tgt["shards"].append(s)
            meta = merged
    if jax.process_index() == coordinator_rank:
        with open(os.path.join(path, _META_NAME), "w") as f:
            json.dump(meta, f)


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id: Optional[int] = None,
                    offload: bool = False):
    """Reference: load_state_dict.py:377 — fills ``state_dict`` in place,
    resharding saved shards onto each target tensor's current sharding."""
    with open(os.path.join(path, _META_NAME)) as f:
        meta = json.load(f)
    files = {}

    def _file(fname):
        if fname not in files:
            files[fname] = np.load(os.path.join(path, fname))
        return files[fname]

    def _assemble(entry) -> np.ndarray:
        full = np.zeros(entry["global_shape"],
                        dtype=np.dtype(entry["dtype"]))
        for sh in entry["shards"]:
            idx = tuple(slice(a, b) for a, b in sh["window"])
            full[idx] = _file(sh["file"])[sh["key"]]
        return full

    def _fill(d, prefix=""):
        for k, v in d.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                _fill(v, prefix=f"{key}.")
                continue
            entry = meta["tensors"].get(key)
            if entry is None:
                continue
            if entry.get("kind") == "object":
                d[k] = entry["value"]
                continue
            full = _assemble(entry)
            if isinstance(v, Tensor):
                tgt = v._data
                sharding = getattr(tgt, "sharding", None)
                arr = jax.device_put(full.astype(tgt.dtype), sharding) \
                    if sharding is not None else jax.numpy.asarray(full)
                v._data = arr
            else:
                d[k] = jax.numpy.asarray(full)

    _fill(state_dict)


class AsyncSaveHandle:
    """Handle for an in-flight async checkpoint (reference capability:
    async save in the checkpoint subsystem — VERDICT r2 recorded the
    sync-only delta). ``result()`` joins and re-raises any writer
    error."""

    def __init__(self, thread, errbox):
        self._thread = thread
        self._err = errbox

    def done(self) -> bool:
        return not self._thread.is_alive()

    def wait(self, timeout=None):
        self._thread.join(timeout)

    def result(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"async checkpoint still writing after {timeout}s")
        if self._err:
            raise self._err[0]


def async_save_state_dict(state_dict: Dict, path: str, process_group=None,
                          coordinator_rank: int = 0,
                          unique_id: Optional[int] = None) -> AsyncSaveHandle:
    """Checkpoint without blocking training: the device->host snapshot
    happens now (so the caller may mutate parameters immediately after
    return); file IO and the metadata merge run on a background thread.

    TPU-native note: the snapshot is the unavoidable synchronous cost
    (HBM->host copy); overlapping the *disk* write is where the win is —
    same structure as the reference's async save worker."""
    import threading

    # snapshot phase (synchronous): host copies of every shard
    snapshot: Dict = {}
    for key, v in _flat_items(state_dict):
        if isinstance(v, Tensor):
            arr = v._data
        elif isinstance(v, (jax.Array, np.ndarray)):
            arr = v
        else:
            snapshot[key] = v
            continue
        if isinstance(arr, jax.Array) and not isinstance(arr, np.ndarray):
            # device-side copy with the SAME sharding: decouples the
            # snapshot from the caller's buffers (donation/mutation of
            # the original cannot touch this copy), while the writer
            # still sees per-shard windows
            import jax.numpy as jnp

            snapshot[key] = jax.block_until_ready(jnp.copy(arr))
        else:
            snapshot[key] = np.asarray(arr)

    errbox: list = []

    def writer():
        try:
            save_state_dict(snapshot, path, process_group,
                            coordinator_rank, unique_id)
        except BaseException as e:   # surfaced via result()
            errbox.append(e)

    th = threading.Thread(target=writer, daemon=True,
                          name="dckpt-async-save")
    th.start()
    return AsyncSaveHandle(th, errbox)


__all__ += ["async_save_state_dict", "AsyncSaveHandle"]
