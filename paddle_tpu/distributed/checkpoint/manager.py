"""CheckpointManager: retention, discovery, auto-resume, preemption.

Reference capability: the fleet elastic stack's checkpoint lifecycle
(python/paddle/distributed/fleet/elastic — restarts are normal
operation, so the checkpoint subsystem must make them cheap) realized
with the discipline of Orbax's CheckpointManager: every save commits
atomically (see this package's commit protocol), retention never
deletes the newest committed step, and discovery trusts only COMMIT
markers — a crashed save's staging dir is garbage to be collected, not
a resume candidate.

Layout: one directory per step under ``root``::

    root/
      step_40/   (committed: COMMIT + checkpoint.manifest + shards)
      step_50/
      step_60.tmp.12345.3/   (in-flight or crashed save — ignored)

``save(step, state)`` applies the save-interval policy, runs sync or
async (via :func:`async_save_state_dict`), and garbage-collects old
steps after each commit. ``restore_latest(state)`` walks committed
steps newest-first, verifies the manifest, and falls back to the
previous committed step when verification fails (counting
``ckpt.restore.fallbacks``). ``install_preemption_hook`` finalizes an
in-flight async save — or takes an emergency sync save of the newest
state it has seen — before the process dies to SIGTERM, which is what
lets preempted ``run_elastic`` jobs resume from the step they were on
rather than the last scheduled save.
"""
from __future__ import annotations

import os
import re
import shutil
import signal
import threading
import time
from typing import Dict, List, Optional

import jax

from ... import monitor as _monitor

__all__ = ["CheckpointManager"]

_STEP_RE = re.compile(r"^step_(\d+)$")
# step_40.tmp.123.4 / step_40.old.123.4 — commit-protocol debris
_DEBRIS_RE = re.compile(r"^step_\d+\.(tmp|old)\.")
_OLD_RE = re.compile(r"^(step_\d+)\.old\.")


def _newest_mtime(d: str) -> float:
    t = os.path.getmtime(d)
    try:
        names = os.listdir(d)
    except OSError:
        return t
    for n in names:
        try:
            t = max(t, os.path.getmtime(os.path.join(d, n)))
        except OSError:
            pass
    return t


class CheckpointManager:
    """Fault-tolerant checkpoint lifecycle over one root directory.

    Parameters
    ----------
    root: directory holding one ``step_<N>`` subdir per checkpoint.
    keep_last_n: committed checkpoints retained; older ones are deleted
        after each successful save (the newest committed step is never
        deleted, whatever the setting).
    save_interval_steps: ``save(step)`` is a no-op unless
        ``step % save_interval_steps == 0`` (or ``force=True``).
    async_save: stage device->host now, write+commit on a background
        thread; the next ``save()``/``wait()`` finalizes the previous
        one first, so at most one save is in flight.
    coordinator_rank: the process that renames/commits/GCs.
    """

    def __init__(self, root: str, keep_last_n: int = 3,
                 save_interval_steps: int = 1, async_save: bool = False,
                 coordinator_rank: int = 0):
        if keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
        if save_interval_steps < 1:
            raise ValueError("save_interval_steps must be >= 1, got "
                             f"{save_interval_steps}")
        self.root = os.path.normpath(root)
        self.keep_last_n = keep_last_n
        self.save_interval_steps = save_interval_steps
        self.async_save = async_save
        self.coordinator_rank = coordinator_rank
        os.makedirs(self.root, exist_ok=True)
        self._mu = threading.RLock()
        self._gc_mu = threading.Lock()   # serializes gc() runs
        self._pending = None          # (step, AsyncSaveHandle)
        # newest state handed to save(), committed or not: the
        # preemption hook's emergency-save source
        self._last_seen: Optional[tuple] = None   # (step, state_dict)
        self._prev_handlers: dict = {}
        if jax.process_index() == self.coordinator_rank:
            self._recover_graveyards()

    # -- discovery ----------------------------------------------------------

    def _step_path(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step}")

    def all_steps(self) -> List[int]:
        """Committed steps, ascending. Uncommitted/staging dirs are
        skipped — a crashed save is invisible here."""
        from . import is_committed
        steps = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m and is_committed(os.path.join(self.root, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        """Newest committed step, or None on a fresh start."""
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save ---------------------------------------------------------------

    def should_save(self, step: int) -> bool:
        return step % self.save_interval_steps == 0

    def save(self, step: int, state_dict, force: bool = False,
             blocking: Optional[bool] = None) -> bool:
        """Checkpoint ``state_dict`` as ``step``. Returns False when the
        save-interval policy skips the step (the state — or provider —
        is still remembered for an emergency preemption save).
        ``state_dict`` may be a zero-arg callable returning the state:
        it is only materialized when a save actually happens, so
        callers on the per-batch hot path don't pay a full
        state-dict/optimizer traversal for interval-skipped steps.
        ``blocking`` overrides the manager's async default for this
        call."""
        with self._mu:
            self._last_seen = (step, state_dict)
            if not force and not self.should_save(step):
                return False
            if callable(state_dict):
                state_dict = state_dict()
            # one save in flight: finalize the previous before staging
            # the next. The training thread's time in here — staging,
            # sync writes, finalizing the previous async save — bills
            # to the active StepTimer's checkpoint phase (and the
            # train.step.checkpoint_ms histogram) so step-timeline
            # accounting sees checkpoint stalls without the loop
            # threading its timer into this manager.
            with _monitor.ambient_phase("checkpoint"):
                self._finalize_pending_locked()
                sync = not self.async_save if blocking is None \
                    else blocking
                if sync:
                    from . import save_state_dict
                    save_state_dict(state_dict, self._step_path(step),
                                    coordinator_rank=self.coordinator_rank)
                    self._after_commit_locked(step)
                else:
                    from . import async_save_state_dict
                    handle = async_save_state_dict(
                        state_dict, self._step_path(step),
                        coordinator_rank=self.coordinator_rank)
                    self._pending = (step, handle)
            return True

    def wait(self):
        """Finalize any in-flight async save (join + retention GC).
        Re-raises a writer error. Returns only after retention is
        settled (the async path runs GC on a background thread; this
        runs one synchronously behind it)."""
        with self._mu:
            self._finalize_pending_locked()
        self.gc()

    def _finalize_pending_locked(self):
        if self._pending is None:
            return
        step, handle = self._pending
        self._pending = None
        handle.result()            # joins; re-raises writer errors
        self._after_commit_locked(step)

    def _after_commit_locked(self, step: int):
        if self._last_seen is not None and self._last_seen[0] <= step:
            # this state is now durable — drop the emergency-save ref
            # (keeping it would pin one full model copy per manager)
            self._last_seen = (step, None)
        if self.async_save:
            # rmtree of a multi-GB evicted checkpoint can take seconds
            # on a network filesystem — an async-save manager must not
            # bill that to the training thread (which is where this
            # runs, via the next save()'s finalize)
            threading.Thread(target=self._gc_quiet, daemon=True,
                             name="ckpt-gc").start()
        else:
            self.gc()

    def _gc_quiet(self):
        try:
            self.gc()
        except Exception as e:
            import sys
            print(f"[checkpoint] retention GC failed: {e}", file=sys.stderr)

    # -- recovery -----------------------------------------------------------

    def _recover_graveyards(self):
        """A kill inside the commit protocol's overwrite window (save
        onto an existing committed step) strands the only good copy at
        ``step_<N>.old.<uid>``: rename it back instead of letting the
        debris sweep collect it. An uncommitted half-renamed dir at the
        step path loses to a committed graveyard."""
        from . import is_committed
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return
        for name in names:
            m = _OLD_RE.match(name)
            if m is None:
                continue
            full = os.path.join(self.root, name)
            dest = os.path.join(self.root, m.group(1))
            if not is_committed(full) or is_committed(dest):
                continue        # nothing to save / a committed dir won
            try:
                if os.path.exists(dest):
                    shutil.rmtree(dest, ignore_errors=True)
                os.rename(full, dest)
            except OSError:
                continue
            import sys
            print(f"[checkpoint] recovered {m.group(1)} from interrupted "
                  "overwrite", file=sys.stderr)

    # -- retention ----------------------------------------------------------

    def gc(self):
        """Delete committed steps beyond ``keep_last_n`` (never the
        newest), plus crash debris: stale staging/graveyard dirs and
        cold uncommitted ``step_<N>`` dirs (a kill between the rename
        and the COMMIT write leaves one at the final path). Only the
        coordinator deletes — on a shared filesystem every other host
        would race it."""
        from . import is_committed
        if jax.process_index() != self.coordinator_rank:
            return
        with self._gc_mu:
            self._gc_locked(is_committed)

    def _gc_locked(self, is_committed):
        # stranded committed graveyards must be rescued BEFORE the
        # debris sweep below can consider them collectible
        self._recover_graveyards()
        steps = self.all_steps()
        doomed = steps[:-self.keep_last_n] if len(steps) > self.keep_last_n \
            else []
        for step in doomed:
            shutil.rmtree(self._step_path(step), ignore_errors=True)
            _monitor.inc("ckpt.gc.deleted",
                         doc="checkpoints removed by retention GC")
        pending_step = self._pending[0] if self._pending is not None else None
        in_flight = f"step_{pending_step}." if pending_step is not None \
            else None
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return
        for name in names:
            full = os.path.join(self.root, name)
            m = _STEP_RE.match(name)
            if _DEBRIS_RE.match(name):
                if in_flight and name.startswith(in_flight):
                    continue
            elif m and not is_committed(full):
                if pending_step is not None and int(m.group(1)) == \
                        pending_step:
                    continue
            else:
                continue
            # only collect cold debris: a live save from another manager
            # keeps its shard FILE's mtime fresh while streaming (the
            # dir mtime freezes after file creation), so take the newest
            # mtime across the dir and its entries
            try:
                if time.time() - _newest_mtime(full) < 60.0:
                    continue
            except OSError:
                continue
            shutil.rmtree(full, ignore_errors=True)
            _monitor.inc("ckpt.gc.debris",
                         doc="crash debris dirs removed by GC (staging, "
                             "graveyards, uncommitted step dirs)")

    # -- restore ------------------------------------------------------------

    def restore(self, step: int, state_dict: Dict, verify: bool = True):
        """Load committed ``step`` into ``state_dict`` in place
        (manifest-verified unless the caller already verified; reshards
        onto current placements)."""
        from . import load_state_dict
        load_state_dict(state_dict, self._step_path(step), verify=verify)

    def restore_latest(self, state_dict: Dict) -> Optional[int]:
        """Load the newest committed checkpoint that passes manifest
        verification into ``state_dict``; fall back to the previous
        committed one on corruption. Returns the restored step, or None
        when no usable checkpoint exists (state_dict untouched).

        Multi-host: hosts AGREE on the step before loading (candidate
        sets are intersected and each candidate's local verification is
        all-gathered), so a checkpoint that is torn or not yet visible
        on one host can never make workers resume from different
        steps."""
        import zlib

        from . import CheckpointError, verify_checkpoint
        candidates = list(reversed(self.all_steps()))
        multi = jax.process_count() > 1
        tag_base = None
        if multi:
            from . import _begin_tagged_op_and_reclaim, _note_tagged_key
            from .. import collective as _coll
            gen = _begin_tagged_op_and_reclaim(self.root)
            tag_base = (f"dckptr{zlib.crc32(self.root.encode()):08x}"
                        f"g{gen}")
            sets: list = []
            _coll.all_gather_object(sets, candidates,
                                    tag=f"{tag_base}.steps")
            _note_tagged_key(self.root, f"{tag_base}.steps")
            common = set(sets[0])
            for s in sets[1:]:
                common &= set(s)
            candidates = sorted(common, reverse=True)
        for i, step in enumerate(candidates):
            if multi:
                try:
                    verify_checkpoint(self._step_path(step))
                    ok = True
                except CheckpointError:
                    ok = False
                from .. import collective as _coll
                from . import _note_tagged_key
                oks: list = []
                _coll.all_gather_object(oks, ok,
                                        tag=f"{tag_base}.v{step}")
                _note_tagged_key(self.root, f"{tag_base}.v{step}")
                if not all(oks):
                    _monitor.inc(
                        "ckpt.restore.fallbacks",
                        doc="restores that skipped corrupt checkpoints")
                    continue
            try:
                # multi-host: the agreement round just CRC-verified this
                # dir — don't pay the full read again inside the load
                self.restore(step, state_dict, verify=not multi)
                if i and not multi:
                    _monitor.inc(
                        "ckpt.restore.fallbacks", i,
                        doc="restores that skipped corrupt checkpoints")
                return step
            except (CheckpointError, OSError, ValueError, KeyError) as e:
                import sys
                print(f"[checkpoint] step_{step} unusable "
                      f"({type(e).__name__}: {e}); falling back",
                      file=sys.stderr)
                if multi:
                    # verification passed everywhere but the LOAD failed
                    # locally: divergence is now unavoidable without
                    # another agreement round — fail hard rather than
                    # silently resume from a different step than peers
                    raise
        return None

    # -- preemption ---------------------------------------------------------

    def finalize_on_preemption(self, timeout: float = 8.0):
        """Make the newest known state durable before the process dies:
        join an in-flight async save (bounded — the launcher escalates
        SIGTERM to SIGKILL after a grace window, and a peer-less
        multi-host writer can block on the dead coordinator), then — if
        the newest state handed to ``save()`` was interval-skipped and
        is newer than anything committed — take an emergency sync save
        of it."""
        import sys

        # Preemption black box FIRST: the flight record must capture
        # what the process was doing when SIGTERM landed, before the
        # finalize/emergency-save below rewrites the metrics story (and
        # before anything here can block into the kill escalation).
        # The dump runs on a helper thread with a bounded join: this
        # handler executes ON the interrupted thread, which may hold
        # the trace-ring or registry locks — dumping inline would
        # deadlock the whole grace window. Off-thread, a held lock
        # merely delays the dump until the handler returns and the
        # interrupted frame releases it.
        try:
            from ...monitor import trace as _trace
            t = threading.Thread(
                target=_trace.record_fault,
                args=("preemption.sigterm", "preempt"), daemon=True)
            t.start()
            t.join(timeout=2.0)
        except Exception:
            pass
        with self._mu:
            if self._pending is not None:
                step, handle = self._pending
                try:
                    handle.result(timeout=timeout)
                    self._pending = None
                    self._after_commit_locked(step)
                except TimeoutError:
                    print(f"[checkpoint] in-flight save of step {step} "
                          f"still writing after {timeout}s; dying "
                          "without it", file=sys.stderr)
                    return
                except BaseException as e:
                    self._pending = None
                    print(f"[checkpoint] in-flight save failed during "
                          f"preemption: {e}", file=sys.stderr)
            if self._last_seen is not None:
                step, state = self._last_seen
                latest = self.latest_step()
                if state is not None and (latest is None or step > latest):
                    if jax.process_count() > 1:
                        # a committed save is a collective; hosts reach
                        # their SIGTERM hooks independently, so starting
                        # one here can only block on peers that already
                        # died — burn no grace time on it
                        print("[checkpoint] multi-host preemption: "
                              f"step {step} was never saved and cannot "
                              "be emergency-saved without peers",
                              file=sys.stderr)
                    else:
                        _monitor.inc("ckpt.preempt.emergency_saves",
                                     doc="sync saves taken in SIGTERM hooks")
                        self.save(step, state, force=True, blocking=True)

    def install_preemption_hook(self, signals=(signal.SIGTERM,),
                                resend: bool = True):
        """On each signal: finalize (see ``finalize_on_preemption``),
        then chain to the previously-installed handler — or, with
        ``resend=True`` and a default handler, re-deliver the signal so
        the process still dies with the right status. No-op off the
        main thread (signal.signal would raise)."""
        def _handler(signum, frame):
            self.finalize_on_preemption()
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif resend:
                signal.signal(signum, prev if prev is not None
                              else signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        for sig in signals:
            try:
                self._prev_handlers[sig] = signal.signal(sig, _handler)
            except ValueError:      # not the main thread
                return False
        return True

    def remove_preemption_hook(self):
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            except ValueError:
                pass
        self._prev_handlers.clear()

    def close(self):
        """Finalize the in-flight save and detach signal handlers; the
        emergency-save reference is dropped so a closed manager can
        never commit a stale state under a stale step number."""
        self.wait()
        self.remove_preemption_hook()
        with self._mu:
            self._last_seen = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
