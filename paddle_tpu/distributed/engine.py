"""Auto-parallel Engine: cost-model-driven parallel plans (VERDICT-r4
item 8).

Reference capability: `auto_parallel/static/engine.py:63` (Engine — the
high-level auto-parallel API whose planner + `static/cost/` cost model
CHOOSE the distributed plan for a model, then compile and run it) and
`static/cost/` (op-level cost estimation feeding the planner).
TPU-native redesign: planning reuses the auto-tuner's machinery —
candidate factorizations of the chip count, the analytic HBM model, the
reference-style heuristic pruners, and the relative step-time cost model
(`distributed/auto_tuner`) — and the chosen plan materialises as a
`jax.sharding.Mesh` over ('dp','fsdp','tp') axes that GSPMD-sharded
models consume directly. Execution stays single-controller: `Engine`
wraps the planned mesh around the DistModel step surface instead of
partitioning a static program per rank.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import enforce as E
from .auto_tuner import (AutoTuner, default_cost, estimate_memory_bytes,
                         generate_candidates)

__all__ = ["ParallelPlan", "PipelineConfig", "plan_parallel", "Engine"]


@dataclass
class PipelineConfig:
    """A pp>1 plan materialised for the pipeline runtime: the knobs
    `pipeline_spmd`/`make_pipeline_train_step` need, derived from the
    planner's candidate (reference: the Engine's planner feeds
    PipelineParallel's chunk/micro-batch settings the same way)."""

    num_stages: int
    num_micro: int                 # micro-batches per dp replica
    micro_batch_size: int
    axis: str = "pp"
    schedule: str = "gpipe-spmd"   # compiled collective-permute pipeline


@dataclass
class ParallelPlan:
    """A chosen parallel configuration plus its simulated cost."""

    config: Dict[str, Any]             # auto_tuner candidate dict
    world: int
    cost: float                        # default_cost of the pick
    naive_cost: float                  # pure data-parallel baseline
    candidates_considered: int = 0
    candidates_feasible: int = 0
    alternatives: List[Dict] = field(default_factory=list)
    global_batch_size: int = 8

    @property
    def mesh_shape(self):
        """(dp, fsdp, tp) — sharding_degree rides the 'fsdp' axis, mp the
        'tp' axis. pp (if chosen) is not part of this triple; build_mesh
        appends it as the trailing mesh axis for pp>1 plans."""
        c = self.config
        return (c["dp_degree"], c["sharding_degree"], c["mp_degree"])

    @property
    def pp_degree(self) -> int:
        return self.config["pp_degree"]

    def build_mesh(self, devices=None):
        """The plan's Mesh. pp==1: ('dp','fsdp','tp'). pp>1: the pp axis
        joins the mesh as the trailing axis so the pipeline's
        collective-permutes ride neighbouring devices (ICI-adjacent)."""
        import jax
        from jax.sharding import Mesh

        devs = list(devices if devices is not None else jax.devices())
        need = int(np.prod(self.mesh_shape)) * self.pp_degree
        E.enforce_le(need, len(devs),
                     "plan needs more devices than available")
        dp, sh, mp = self.mesh_shape
        if self.pp_degree > 1:
            return Mesh(
                np.array(devs[:need]).reshape(dp, sh, mp, self.pp_degree),
                ("dp", "fsdp", "tp", "pp"))
        return Mesh(np.array(devs[:dp * sh * mp]).reshape(dp, sh, mp),
                    ("dp", "fsdp", "tp"))

    def pipeline_config(self) -> Optional["PipelineConfig"]:
        """Materialise a pp>1 pick for the pipeline runtime; None when
        the plan has no pipeline dimension. num_micro follows the cost
        model's own convention (acc_steps = gbs / (dp*sh) / mbs — the
        batch splits over BOTH data-parallel-like axes before
        micro-batching), so the built step does exactly the work the
        plan was costed for."""
        if self.pp_degree == 1:
            return None
        c = self.config
        mbs = c["micro_batch_size"]
        if "acc_steps" in c:
            num_micro = int(c["acc_steps"])
        else:
            ways = c["dp_degree"] * c["sharding_degree"] * mbs
            E.enforce_gt(self.global_batch_size, 0, "global_batch_size")
            E.enforce(self.global_batch_size % ways == 0,
                      f"global batch {self.global_batch_size} not "
                      f"divisible by dp*sharding*micro_batch = {ways}")
            num_micro = self.global_batch_size // ways
        return PipelineConfig(num_stages=self.pp_degree,
                              num_micro=num_micro,
                              micro_batch_size=mbs)

    def build_pipeline_step(self, stage_fn, loss_fn, *, lr: float = 1e-3,
                            remat: bool = True, devices=None):
        """Wire a pp>1 plan into the compiled collective-permute
        pipeline: returns (jitted step, mesh, PipelineConfig). The step
        takes stage-stacked params (leading axis = num_stages, placed
        with `shard_stage_params`), a [num_micro*mbs, ...] batch, and
        labels."""
        from .pipeline import make_pipeline_train_step

        pc = self.pipeline_config()
        if pc is None:
            raise E.InvalidArgumentError(
                "plan chose pp=1 — no pipeline schedule to build",
                hint="a pp=1 plan runs as a plain GSPMD step; "
                     "build_pipeline_step is for pp>1 plans")
        mesh = self.build_mesh(devices)
        step = make_pipeline_train_step(stage_fn, loss_fn, mesh,
                                        num_micro=pc.num_micro,
                                        axis=pc.axis, lr=lr, remat=remat)
        return step, mesh, pc

    def describe(self) -> str:
        dp, sh, mp = self.mesh_shape
        est = self.config.get("estimated_memory_bytes")
        mem = f", est {est / 1e9:.1f} GB/chip" if est else ""
        return (f"plan: dp={dp} fsdp={sh} tp={mp} pp={self.pp_degree} "
                f"mbs={self.config['micro_batch_size']} "
                f"cost={self.cost:.4g} (naive dp-only: "
                f"{'infeasible' if math.isinf(self.naive_cost) else f'{self.naive_cost:.4g}'}"
                f"){mem}")


def plan_parallel(n_devices: int, model_cfg: Dict, *,
                  global_batch_size: int = 8,
                  hbm_bytes: float = 95e9,
                  chips_per_host: int = 4,
                  sharding_stage: int = 3,
                  use_recompute: bool = True,
                  tuner_overrides: Optional[Dict] = None) -> ParallelPlan:
    """Choose (dp, fsdp, tp, pp, mbs) for ``model_cfg`` on ``n_devices``
    chips: enumerate factorizations, prune by the analytic HBM model and
    the reference heuristics, rank by the relative step-time cost model,
    and return the argmin together with the naive pure-data-parallel
    baseline cost (``inf`` when naive DP does not fit — the common case
    that motivates the planner)."""
    tuner_cfg = {
        "num_chips": int(n_devices),
        "global_batch_size": int(global_batch_size),
        "max_mem_usage": float(hbm_bytes),
        "chips_per_host": int(chips_per_host),
        "sharding_stage": int(sharding_stage),
        "use_recompute": bool(use_recompute),
        "model_cfg": dict(model_cfg),
    }
    tuner_cfg.update(tuner_overrides or {})
    tuner = AutoTuner(tuner_cfg)
    feasible = tuner.candidates            # pruned + cost-sorted
    considered = len(generate_candidates(tuner_cfg))
    if not feasible:
        raise E.ResourceExhaustedError(
            f"no parallel plan fits {model_cfg.get('num_params', '?')} "
            f"params on {n_devices} chips x {hbm_bytes / 1e9:.0f} GB",
            hint="raise hbm_bytes, add chips, or enable recompute/"
                 "sharding_stage=3")
    best = feasible[0]

    # naive baseline: pure data parallel, largest micro-batch
    naive = None
    for c in generate_candidates(tuner_cfg):
        if (c["dp_degree"] == n_devices and c["mp_degree"] == 1
                and c["pp_degree"] == 1 and c["sharding_degree"] == 1):
            if naive is None or c["micro_batch_size"] > \
                    naive["micro_batch_size"]:
                naive = c
    mcfg = tuner_cfg["model_cfg"]
    naive_cost = math.inf
    if naive is not None and estimate_memory_bytes(
            naive, mcfg) <= tuner_cfg["max_mem_usage"]:
        naive_cost = default_cost(naive, mcfg)

    return ParallelPlan(
        config=dict(best), world=int(n_devices),
        cost=default_cost(best, mcfg), naive_cost=naive_cost,
        candidates_considered=considered,
        candidates_feasible=len(feasible),
        alternatives=[dict(c) for c in feasible[1:4]],
        global_batch_size=int(global_batch_size))


def _model_stats(layer) -> Dict:
    """Best-effort model_cfg extraction from a live Layer."""
    n_params = 0
    hidden = 0
    for p in layer.parameters():
        n_params += int(np.prod(p.shape))
        if len(p.shape) >= 2:
            hidden = max(hidden, int(min(p.shape[-2:])))
    sublayers = getattr(layer, "sublayers", lambda: [])()
    return {"num_params": float(max(n_params, 1)),
            "num_layers": max(len(sublayers), 1),
            "hidden_size": max(hidden, 1),
            "seq_length": 2048, "dtype": "bfloat16"}


class Engine:
    """High-level auto-parallel API (reference: engine.py:63): wraps a
    model + loss + optimizer, PLANS the distributed layout with the cost
    model, and serves train/eval/predict steps on the planned mesh.

    Unlike the reference there is no partitioned static program per
    rank — the plan is a GSPMD mesh + sharding hints consumed by jit —
    so `prepare()` is where the planning happens and `fit/evaluate/
    predict` run the single-controller step loop."""

    def __init__(self, model=None, loss=None, optimizer=None,
                 metrics=None, cluster=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy
        self.plan: Optional[ParallelPlan] = None
        self.mesh = None
        self.pipeline: Optional[PipelineConfig] = None

    # -- planning ------------------------------------------------------------
    def prepare(self, model_cfg: Optional[Dict] = None,
                n_devices: Optional[int] = None,
                **plan_kwargs) -> ParallelPlan:
        """Run the planner. ``model_cfg`` (num_params/num_layers/
        hidden_size/seq_length) defaults to stats read off the model;
        ``n_devices`` defaults to the visible device count."""
        import jax

        if n_devices is None:
            n_devices = len(jax.devices())
        if model_cfg is None:
            E.enforce_not_none(self.model, "Engine.model",
                               hint="pass model_cfg= explicitly when "
                                    "planning without a model")
            model_cfg = _model_stats(self.model)
        self.plan = plan_parallel(int(n_devices), model_cfg,
                                  **plan_kwargs)
        self.mesh = self.plan.build_mesh()
        self.pipeline = self.plan.pipeline_config()
        return self.plan

    # -- execution (single-controller step surface) --------------------------
    def _step(self, *args, train: bool):
        E.enforce_not_none(self.model, "Engine.model")
        inputs, labels = args[:-1], args[-1]
        out = self.model(*inputs)
        loss = self.loss(out, labels) if self.loss is not None else out
        if train:
            E.enforce_not_none(self.optimizer, "Engine.optimizer",
                               hint="fit() needs an optimizer")
            loss.backward()
            self.optimizer.step()
            self.optimizer.clear_grad()
        return loss

    def fit(self, train_data, epochs: int = 1, verbose: int = 0,
            callbacks=None) -> List[float]:
        if self.plan is None and self.model is not None:
            try:
                self.prepare()
            except E.ResourceExhaustedError:
                pass        # tiny single-device runs: no plan needed
        if self.model is not None:
            self.model.train()
        losses = []
        for _ in range(int(epochs)):
            for batch in train_data:
                loss = self._step(*batch, train=True)
                losses.append(float(loss))
        return losses

    def evaluate(self, eval_data) -> float:
        if self.model is not None:
            self.model.eval()
        total, n = 0.0, 0
        for batch in eval_data:
            total += float(self._step(*batch, train=False))
            n += 1
        E.enforce_gt(n, 0, "evaluate() got an empty loader")
        return total / n

    def predict(self, test_data) -> List:
        if self.model is not None:
            self.model.eval()
        return [self.model(*batch if isinstance(batch, (tuple, list))
                           else (batch,)) for batch in test_data]
