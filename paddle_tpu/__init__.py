"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference: /root/reference), built on JAX/XLA/Pallas.

Top-level namespace mirrors ``paddle.*``: tensor ops, nn, optimizer, amp, io,
distributed, jit, static-analogue compiled path. The compute path is pure
JAX (XLA on TPU); eager autograd is a tape over jax.vjp closures
(see autograd/tape.py); distributed is mesh/GSPMD-first (see distributed/).
"""
from __future__ import annotations

__version__ = "0.1.0"

from .core import dtype as _dtype_mod
from .core.dtype import (bfloat16, bool_, complex64, complex128, float16,  # noqa
                         float32, float64, get_default_dtype, int8, int16,
                         int32, int64, set_default_dtype, uint8)
from .core.flags import get_flags, set_flags  # noqa
from .core.state import enable_grad, no_grad, set_grad_enabled  # noqa
from .core.tensor import Parameter, Tensor, to_tensor  # noqa
from .framework.random import get_rng_state, seed, set_rng_state  # noqa

# Flat op namespace (paddle.* functional surface).
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation

from . import autograd  # noqa
from . import amp  # noqa

# NOTE: `from . import linalg` would be satisfied by the `linalg` attribute
# the ops star-import leaked onto this package (ops.linalg); import the real
# namespace modules explicitly so paddle_tpu.linalg is linalg.py.
import importlib as _importlib

linalg = _importlib.import_module(".linalg", __name__)
fft = _importlib.import_module(".fft", __name__)
signal = _importlib.import_module(".signal", __name__)
from .signal import istft, stft  # noqa
from .ops.manipulation_ext import tensor_unfold as unfold  # noqa
from . import distributed  # noqa
from . import io  # noqa
from . import jit  # noqa
from . import nn  # noqa
from . import optimizer  # noqa
from . import kernels  # noqa
from . import models  # noqa
from . import incubate  # noqa
from . import metric  # noqa
from . import monitor  # noqa
from . import profiler  # noqa
from . import static  # noqa
from . import inference  # noqa
from . import vision  # noqa
from . import quantization  # noqa
from . import sparse  # noqa
from . import geometric  # noqa
from . import audio  # noqa
from . import text  # noqa
from . import distribution  # noqa
from . import hapi  # noqa
from .hapi import Model, summary  # noqa
from .hapi import callbacks  # noqa
from .framework.io import load, save  # noqa
from .framework.io import async_save, clear_async_save_task_queue  # noqa
from .framework.compat import (CPUPlace, CUDAPinnedPlace, CUDAPlace,  # noqa
                               LazyGuard, TPUPlace, batch,
                               disable_signal_handler, finfo, flops, iinfo,
                               set_printoptions)
from .framework.random import (get_rng_state as get_cuda_rng_state,  # noqa
                               set_rng_state as set_cuda_rng_state)
from .core.state import grad_enabled as is_grad_enabled  # noqa
from .nn import ParamAttr  # noqa
from .distributed.parallel import DataParallel  # noqa

# paddle.bool / paddle.dtype aliases (reference: paddle.dtype vocabulary)
bool = bool_  # noqa: A001
import numpy as _np
dtype = _np.dtype

import jax as _jax
from .core import enforce as E


def check_shape(shape):
    """Validate a shape argument (reference: static check in utils.py):
    ints, or a 1-D integer list/tuple with at most one -1."""
    if isinstance(shape, (list, tuple)):
        # NB: builtins.sum — paddle.sum (the tensor op) shadows it here
        import builtins
        neg = builtins.sum(1 for s in shape
                           if isinstance(s, int) and s < 0)
        if neg > 1:
            raise E.InvalidArgumentError(f"shape can carry at most one -1, got {shape}")


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return len(_jax.devices())


def get_device() -> str:
    d = _jax.devices()[0]
    return f"{d.platform}:{d.id}"


def set_device(device: str):
    # Placement is managed by XLA/shardings; accepted for API parity.
    return device


def grad(*args, **kwargs):
    return autograd.grad(*args, **kwargs)


# -- static-mode toggles (reference: base/framework.py enable_static) -------
_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


def in_static_mode() -> bool:
    return _static_mode


# Pallas kernels self-select on TPU backends (KernelFactory-style dispatch).
kernels.auto_register()

# Composite/creation/inplace op families join the dispatch registry
# (reference OpInfoMap parity; ops/composite.py).
from .ops import composite as _composite
_composite.register_composites()

# round-3 namespace completion: device/callbacks/hub/onnx/regularizer/
# tensor/reader aliases + amp.debugging + utils surface
from . import device  # noqa: E402
# NB: `from . import callbacks` would be satisfied by the hapi.callbacks
# attribute bound above; import the real top-level module explicitly.
callbacks = _importlib.import_module(".callbacks", __name__)
from . import hub  # noqa: E402
from . import onnx  # noqa: E402
from . import regularizer  # noqa: E402
from . import tensor  # noqa: E402
from . import reader  # noqa: E402
from . import version  # noqa: E402
from . import utils  # noqa: E402
from .amp import debugging as _amp_debugging  # noqa: E402,F401


def tolist(x):
    """Free-function form of Tensor.tolist (reference binds both)."""
    return x.tolist() if hasattr(x, "tolist") else list(x)
