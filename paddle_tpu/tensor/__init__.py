"""paddle.tensor namespace parity (reference: python/paddle/tensor/ —
the per-domain op modules re-exported flat). The ops package is the
single source; this module aliases it so ``paddle.tensor.creation`` /
``paddle.tensor.math`` style imports from reference recipes resolve."""
from ..ops import *  # noqa: F401,F403
from ..ops import (creation, linalg, logic, manipulation, math,  # noqa
                   random, reduction)

# reference submodule aliases
search = logic
attribute = logic
stat = reduction
einsum = math

# signal ops re-exported flat like the reference tensor/__init__
from ..ops.fft_ops import istft, stft  # noqa: F401
from ..ops.manipulation_ext import tensor_unfold as unfold  # noqa: F401
from .. import set_printoptions  # noqa: F401


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    """Legacy creation API (reference: tensor/creation.py fill_constant);
    paddle.full with the fluid argument order."""
    from ..ops.creation import full
    return full(shape, value, dtype=dtype)


# -- TensorArray family (reference: tensor/array.py — LoDTensorArray) -------
# TPU-native shape: a TensorArray is a plain Python list of Tensors in
# eager mode (the reference's dygraph path does exactly this,
# tensor/array.py:88 "In dynamic mode, array is a Python list"); inside
# jit-traced code use lax.scan/stacked tensors instead.

def create_array(dtype="float32", initialized_list=None):
    if initialized_list is not None:
        return list(initialized_list)
    return []


def array_length(array):
    return len(array)


def array_read(array, i):
    return array[int(i)]


def array_write(x, i, array=None):
    if array is None:
        array = []
    i = int(i)
    if i < len(array):
        array[i] = x
    elif i == len(array):
        array.append(x)
    else:
        raise IndexError(
            f"array_write index {i} beyond array length {len(array)}")
    return array
