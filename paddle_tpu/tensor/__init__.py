"""paddle.tensor namespace parity (reference: python/paddle/tensor/ —
the per-domain op modules re-exported flat). The ops package is the
single source; this module aliases it so ``paddle.tensor.creation`` /
``paddle.tensor.math`` style imports from reference recipes resolve."""
from ..ops import *  # noqa: F401,F403
from ..ops import (creation, linalg, logic, manipulation, math,  # noqa
                   random, reduction)

# reference submodule aliases
search = logic
attribute = logic
stat = reduction
einsum = math
