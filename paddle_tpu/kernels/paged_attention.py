"""Pallas TPU ragged paged attention (decode).

Reference capability: the vLLM-style PagedAttention decode kernel
(csrc/attention/paged_attention_v1.cu in the reference serving stacks) as
rebuilt TPU-native by Ragged Paged Attention (arxiv 2604.15464): each
sequence's KV cache lives in non-contiguous fixed-size pages named by a
block table, and one decode query attends over exactly its own ragged
length — no batch-uniform max-length padding in either HBM traffic or
FLOPs.

TPU-native design (follows flash_attention.py's canonical pattern):
- Grid ``(batch, kv_heads, max_pages)`` with the page axis sequential per
  core, carrying the online-softmax running max/denominator in VMEM
  scratch exactly like the flash forward.
- The block table and per-request lengths ride a
  ``PrefetchScalarGridSpec`` scalar prefetch: the K/V BlockSpec index
  maps read ``block_table[b, p]`` to aim the automatic HBM->VMEM DMA at
  the right page — the gather IS the BlockSpec, no in-kernel DMA code.
- Pages past a sequence's length are predicated off (``pl.when``), so a
  short sequence in a long-batch grid costs control flow only; the
  final partial page is masked per-position. A length of 0 (empty slot
  in the serving engine's fixed slot grid) produces a zero output row.
- GQA: queries reshape to [B, kv_heads, group, head_dim]; the group dim
  is zero-padded to the sublane tile so every matmul is legal.

Layouts: pages are ``[num_pages, kv_heads, page_size, head_dim]`` (the
kv-head axis OUTSIDE the page axis so a (1, 1, page, hd) block satisfies
Mosaic's last-two-dims tiling rule for any page size); q is
``[batch, num_heads, head_dim]`` — one decode position per sequence.

``paged_attention_ref`` is the pure-jnp gather fallback — identical
math, runs on every backend — which tier-1 exercises on CPU and the
dispatcher (kernels/__init__.py) uses when the kernel is unsupported.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype).itemsize == 2 else 8


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _decode_kernel(bt_ref, len_ref, *refs, scale, page_size, max_pages,
                   quant):
    if quant:
        # int8 pages ride with per-(page, kv-head) scale scalars (SMEM,
        # same block-table index map): dequant is a scalar multiply
        # FOLDED into the dots — the page DMA itself stays int8
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc, m_s, l_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc, m_s, l_s = refs
    b = pl.program_id(0)
    pi = pl.program_id(2)
    length = len_ref[b]
    npages = (length + page_size - 1) // page_size

    @pl.when(pi == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # a page wholly past this sequence's length contributes nothing —
    # the ragged skip that makes mixed-length batches cheap
    @pl.when(pi < npages)
    def _body():
        q = q_ref[0, 0]                                  # [gp, hd]
        k = k_ref[0, 0]                                  # [ps, hd]
        if quant:
            # every code in this (page, head) block shares ONE scale,
            # so dot(q, codes) * (ks*scale) == dot(q, deq(codes)) * scale
            s = jax.lax.dot_general(
                q.astype(jnp.float32), k.astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) \
                * (ks_ref[0, 0] * scale)                 # [gp, ps]
        else:
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
        pos = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, _NEG_INF)         # partial last page

        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_s[:] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=1, keepdims=True), l_s.shape)
        if quant:
            acc[:] = acc[:] * alpha + jax.lax.dot_general(
                p, v_ref[0, 0].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * vs_ref[0, 0]
        else:
            acc[:] = acc[:] * alpha + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0, 0],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    @pl.when(pi == max_pages - 1)
    def _finalize():
        l = l_s[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)                  # empty slot -> 0
        o_ref[0, 0] = (acc[:] / l).astype(o_ref.dtype)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                           scale=None, k_scales=None, v_scales=None,
                           interpret=None):
    """Paged decode attention. q: [B, num_heads, head_dim]; k_pages /
    v_pages: [num_pages, kv_heads, page_size, head_dim]; block_tables:
    [B, max_pages] page ids (entries past a sequence's pages may hold
    any value — they are clamped and masked); lengths: [B] valid KV
    positions per sequence (0 = empty slot -> zero output row).

    With ``k_scales``/``v_scales`` ([num_pages, kv_heads] f32, both or
    neither) the pages are int8 codes (FLAGS_serving_kv_quant): each
    (page, kv-head) scale rides the SAME block-table index map as its
    page, lands in SMEM as a (1, 1) scalar block, and dequantization
    folds into the two dots — HBM page traffic stays int8.
    Returns [B, num_heads, head_dim]."""
    quant = k_scales is not None
    B, nh, hd = q.shape
    P, kv, ps, _ = k_pages.shape
    maxp = block_tables.shape[1]
    g = nh // kv
    sub = _sublane(q.dtype)
    gp = max(sub, (g + sub - 1) // sub * sub)
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    if interpret is None:
        interpret = _interpret_default()

    qg = q.reshape(B, kv, g, hd)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    # clamp: padded/garbage table entries must still name a real page for
    # the BlockSpec DMA; their contribution is masked by ``lengths``
    bt = jnp.clip(block_tables, 0, P - 1).reshape(-1).astype(jnp.int32)

    def _page_map(b, h, p, bt_, ln_, mp=maxp):
        return (bt_[b * mp + p], h, 0, 0)

    def _scale_map(b, h, p, bt_, ln_, mp=maxp):
        return (bt_[b * mp + p], h)

    in_specs = [
        pl.BlockSpec((1, 1, gp, hd),
                     lambda b, h, p, bt_, ln_: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, ps, hd), _page_map),
        pl.BlockSpec((1, 1, ps, hd), _page_map),
    ]
    operands = [qg, k_pages, v_pages]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1), _scale_map, memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), _scale_map, memory_space=pltpu.SMEM),
        ]
        operands += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, kv, maxp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, gp, hd),
                               lambda b, h, p, bt_, ln_: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, hd), jnp.float32),
            pltpu.VMEM((gp, 128), jnp.float32),
            pltpu.VMEM((gp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, page_size=ps,
                          max_pages=maxp, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, kv, gp, hd), q.dtype),
        interpret=interpret,
    )(bt, lengths.astype(jnp.int32), *operands)
    return out[:, :, :g, :].reshape(B, nh, hd)


# ---------------------------------------------------------------------------
# pure-jnp fallback (identical math; every backend)
# ---------------------------------------------------------------------------

def paged_attention_ref(q, k_pages, v_pages, block_tables, lengths, *,
                        scale=None, k_scales=None, v_scales=None):
    """Gather-based reference: same contract and masking semantics as the
    kernel (safe softmax — an empty sequence yields a zero row, never
    NaN). This is the path tier-1 runs on CPU. ``k_scales``/``v_scales``
    ([num_pages, kv_heads] f32) mark int8 pages: the gathered codes are
    dequantized in f32 before the same einsum math."""
    B, nh, hd = q.shape
    P, kv, ps, _ = k_pages.shape
    maxp = block_tables.shape[1]
    g = nh // kv
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    bt = jnp.clip(block_tables, 0, P - 1).reshape(-1)
    # flat gathers with in-bounds promise (clip above), consumed in page
    # layout directly — XLA:CPU's generic gather/transpose lowering is
    # this fallback's hot spot, so no moveaxis copies
    k = k_pages.at[bt].get(
        mode="promise_in_bounds").reshape(B, maxp, kv, ps, hd)
    v = v_pages.at[bt].get(
        mode="promise_in_bounds").reshape(B, maxp, kv, ps, hd)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if k_scales is not None:
        sk = k_scales.at[bt].get(
            mode="promise_in_bounds").reshape(B, maxp, kv)
        sv = v_scales.at[bt].get(
            mode="promise_in_bounds").reshape(B, maxp, kv)
        kf = kf * sk.astype(jnp.float32)[..., None, None]
        vf = vf * sv.astype(jnp.float32)[..., None, None]
    qf = q.astype(jnp.float32).reshape(B, kv, g, hd)
    s = jnp.einsum("bkgd,bmkpd->bkgmp", qf, kf) * scale
    pos = jnp.arange(maxp)[:, None] * ps + jnp.arange(ps)[None, :]
    mask = pos[None] < lengths[:, None, None]          # [B, maxp, ps]
    s = jnp.where(mask[:, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=(-2, -1), keepdims=True)
    e = jnp.where(mask[:, None, None], jnp.exp(s - m), 0.0)
    l = jnp.sum(e, axis=(-2, -1), keepdims=True)
    l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bkgmp,bmkpd->bkgd", e / l, vf)
    return out.reshape(B, nh, hd).astype(q.dtype)


def supported(q, k_pages, block_tables, quant=False) -> bool:
    """Whether the pallas kernel handles these shapes (else the
    dispatcher uses paged_attention_ref). ``quant`` marks the int8-page
    arm (scale planes present)."""
    if q.ndim != 3 or k_pages.ndim != 4 or block_tables.ndim != 2:
        return False
    B, nh, hd = q.shape
    P, kv, ps, hd2 = k_pages.shape
    if hd != hd2 or hd > 256 or nh % kv != 0:
        return False
    if jnp.dtype(q.dtype) not in (jnp.dtype(jnp.float32),
                                  jnp.dtype(jnp.bfloat16)):
        return False
    if quant:
        # int8 pages: the K/V block's sublane tile is 32 rows (1-byte
        # dtype), and only int8 codes are a valid quantized pool
        if jnp.dtype(k_pages.dtype) != jnp.dtype(jnp.int8) or ps % 32:
            return False
    elif jnp.dtype(k_pages.dtype) == jnp.dtype(jnp.int8):
        return False     # int8 pool without scales is a contract breach
    # page rows must cover the dtype's sublane tile (16 for bf16) and
    # the lane dim should fill VREGs; anything smaller falls back
    return hd % 8 == 0 and ps % _sublane(q.dtype) == 0 and P >= 1
