"""Runtime kernel autotuning with a persisted cache.

Reference capability: paddle/phi/kernels/autotune/{cache.h,cache_base.h,
switch_autotune.h} — measure candidate algorithms for an op at its actual
runtime shape once, remember the winner keyed by shape/dtype, persist
across processes. There the candidates are cuDNN algos; here they are
Pallas block sizes for the flash-attention kernels (the one knob Mosaic
does not pick for us — XLA autotunes its own fusions already).

TPU-native design:
- Tuning happens at DISPATCH time (trace time): shapes are static under
  jit, so the dispatcher knows the exact (bh, sq, sk, d, dtype, causal)
  the kernel will run at. Candidates are timed with standalone jitted
  fwd+bwd runs on freshly materialised random inputs — real compiles of
  the real kernel at the real shape.
- The winner is cached in-process AND in a JSON file
  (~/.cache/paddle_tpu/autotune.json, override via
  PADDLE_TPU_AUTOTUNE_CACHE) so later processes — including the driver's
  bench — skip straight to the tuned blocks. Writes are atomic
  (tmp + rename).
- Measurement only runs on a real TPU backend (timing interpret-mode
  pallas on CPU is meaningless); elsewhere the defaults return
  immediately. FLAGS use_autotune=False (or env PADDLE_TPU_AUTOTUNE=0)
  freezes everything at the defaults.
"""
from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from ..core import flags as _flags

_flags.define_flag("use_autotune", True,
                   "Measure+cache pallas kernel block sizes per shape "
                   "(reference: phi/kernels/autotune).")

DEFAULT_BLOCKS = (128, 128)
# VERDICT-r3 sweep set: {128,256,512} x {128,256}. Ordered with the
# known-good default first so a timing tie keeps it.
CANDIDATES = ((128, 128), (256, 128), (128, 256), (256, 256),
              (512, 128), (512, 256))
# VMEM working-set bound per candidate (scratch + operand blocks, f32):
# stay well under the ~16M/core budget so Mosaic never has to spill.
_VMEM_BUDGET = 12 * 1024 * 1024


def _cache_path() -> str:
    return os.environ.get(
        "PADDLE_TPU_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "autotune.json"))


class AutotuneCache:
    """shape-key -> chosen config, in-memory with JSON persistence."""

    def __init__(self, path: Optional[str] = None):
        self._explicit_path = path
        self._mem: dict = {}
        self._loaded = False
        self._resolved_path: Optional[str] = None

    @property
    def _path(self) -> str:
        # Resolved lazily, NOT in __init__: the module-level _CACHE is
        # constructed at import time, which may precede the harness
        # setting PADDLE_TPU_AUTOTUNE_CACHE (bench.py imports paddle_tpu
        # before it applies its autotune policy). Freezing the path at
        # construction silently redirected the bench to the empty
        # home-dir cache and cost the tuned blocks.
        if self._explicit_path is not None:
            return self._explicit_path
        return _cache_path()

    def _load(self):
        # PADDLE_TPU_AUTOTUNE_CACHE may change AFTER the first load
        # (tpu_smoke retargets the repo cache mid-process): a stale
        # sticky _loaded would keep serving old-path entries and put()
        # would write their union into the new file (cross-cache
        # contamination, ADVICE r5). Track the last-resolved path and
        # evict when it moves.
        path = self._path
        if self._resolved_path is not None and path != self._resolved_path:
            _monitor.inc("autotune.cache.evictions", len(self._mem),
                         doc="entries dropped on cache-path change")
            self._mem.clear()
            self._loaded = False
        self._resolved_path = path
        if self._loaded:
            return
        self._loaded = True
        try:
            with open(self._path) as f:
                disk = json.load(f)
            if isinstance(disk, dict):
                # disk entries never override fresher in-memory ones
                for k, v in disk.items():
                    self._mem.setdefault(k, v)
        except (OSError, ValueError):
            pass

    def get(self, key: str):
        self._load()
        return self._mem.get(key)

    def get_nearest(self, key: str):
        """Warm-start lookup for a cold shape key: the closest tuned
        entry whose key shares this key's non-numeric skeleton (same
        knob family, backend, dtype — digit runs wildcarded), by
        log-space distance over the numeric fields. A serving shape
        that was never swept (new batch size, new max_len) then seeds
        from its nearest tuned neighbor instead of the hardcoded
        default. Returns ``(neighbor_key, value)`` or ``None``; error
        entries never warm-start."""
        self._load()
        skel = re.sub(r"\d+", "#", key)
        nums = [int(x) for x in re.findall(r"\d+", key)]
        best = None
        best_d = None
        for k in sorted(self._mem):       # deterministic tie-break
            v = self._mem[k]
            if k == key or not isinstance(v, dict) or v.get("error"):
                continue
            if re.sub(r"\d+", "#", k) != skel:
                continue
            kn = [int(x) for x in re.findall(r"\d+", k)]
            if len(kn) != len(nums):
                continue
            d = sum(abs(math.log(a + 1) - math.log(b + 1))
                    for a, b in zip(nums, kn))
            if best_d is None or d < best_d:
                best_d, best = d, (k, v)
        return best

    def put(self, key: str, value: dict):
        self._load()
        self._mem[key] = value
        try:
            # re-merge the file first: a concurrent process may have
            # written other shapes since our load — don't erase them
            # (our own fresh entries win on conflict)
            try:
                with open(self._path) as f:
                    disk = json.load(f)
                if isinstance(disk, dict):
                    for k, v in disk.items():
                        self._mem.setdefault(k, v)
            except (OSError, ValueError):
                pass
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            tmp = f"{self._path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self._mem, f, indent=1, sort_keys=True)
            os.replace(tmp, self._path)
        except OSError:
            pass   # cache is an optimisation; never fail the op

    def clear(self):
        self._mem.clear()
        self._loaded = True


_CACHE = AutotuneCache()

# Shape keys whose sweep failed IN THIS PROCESS: don't re-sweep on every
# retrace (each sweep is minutes of compiles). Persisted error entries
# are honoured as hits only after MAX_SWEEP_FAILURES processes have each
# re-paid the sweep — one transient tunnel death must not pin a healthy
# shape to the defaults forever (self-heal), but a shape that genuinely
# cannot compile must not cost every later process minutes either.
_FAILED_KEYS: set = set()
MAX_SWEEP_FAILURES = 2

# What flash_blocks actually RETURNED in this process, per shape key —
# the benchmark's evidence of which blocks the traced program used
# (distinct from the persisted cache, which holds every shape any prior
# run tuned).
_USED: dict = {}


def used_blocks() -> dict:
    """{shape_key: {"blocks": [bq, bk], "source": cache|measured|default}}
    for every dispatch decision made by this process."""
    return dict(_USED)


def _mode() -> str:
    """PADDLE_TPU_AUTOTUNE: "1" measure+cache (default), "cached" use
    cache hits but never measure (the driver-bench mode — measurement
    compiles must not run inside its watchdog-budgeted trace), "0" off."""
    return os.environ.get("PADDLE_TPU_AUTOTUNE", "1")


def _vmem_bytes(bq: int, bk: int, d: int) -> int:
    # fwd: acc[bq,d] + m/l[bq,128] + q[bq,d] + k/v[bk,d] + s/p[bq,bk]
    # bwd dkv: dk/dv acc[bk,d]*2 + blocks. Take the max-ish superset.
    return 4 * (bq * d * 2 + bq * 128 * 2 + bk * d * 3 + bq * bk * 2)


def flash_candidates(bh, sq, sk, d, dtype):
    """Legal (block_q, block_k) candidates for a flash shape, default
    first."""
    from .tiling import flash_specs_legal

    out = []
    for bq, bk in CANDIDATES:
        bq_, bk_ = min(bq, sq), min(bk, sk)
        if (bq_, bk_) in out:
            continue
        if sq % bq_ or sk % bk_ or bq_ % 8 or bk_ % 8:
            continue
        if _vmem_bytes(bq_, bk_, d) > _VMEM_BUDGET:
            continue
        if not flash_specs_legal(bh, sq, sk, d, bq_, bk_, dtype):
            continue
        out.append((bq_, bk_))
    if not out:
        out.append((min(DEFAULT_BLOCKS[0], sq), min(DEFAULT_BLOCKS[1], sk)))
    return out


def _rand(rng, shape, dtype, scale=1.0):
    # float32 host generation: float64 standard_normal doubles the host
    # bytes for multi-GB sweep operands for no measurement benefit
    return jnp.asarray(
        rng.standard_normal(shape, dtype=np.float32) * scale, dtype)


def _flash_measurer(b, sq, sk, h, kvh, d, dtype, causal):
    """Per-sweep measurement closure: operands materialise ONCE, every
    candidate reuses them (per-candidate regeneration cost minutes of
    host RNG + transfer on large shapes)."""
    # Import from the submodule directly: the package __init__ rebinds
    # the ``flash_attention`` attribute to the function, so a lazy
    # ``from . import flash_attention`` here would get the function.
    from .flash_attention import flash_attention as _flash

    rng = np.random.default_rng(0)
    q = _rand(rng, (b, sq, h, d), dtype)
    k = _rand(rng, (b, sk, kvh, d), dtype)
    v = _rand(rng, (b, sk, kvh, d), dtype)

    def measure(bq, bk, interpret=False):
        def loss(q, k, v):
            return jnp.sum(_flash(
                q, k, v, causal=causal, block_q=bq, block_k=bk,
                interpret=interpret).astype(jnp.float32))

        f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        out = f(q, k, v)                # compile + warmup
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(q, k, v)
            # float() hard-syncs even through the axon tunnel (where
            # block_until_ready can return early)
            float(out[0][0, 0, 0, 0].astype(jnp.float32))
            best = min(best, time.perf_counter() - t0)
        return best

    return measure


def _measure_flash(b, sq, sk, h, kvh, d, dtype, causal, bq, bk,
                   interpret=False) -> float:
    """One-shot measurement (tests); sweeps use _flash_measurer."""
    return _flash_measurer(b, sq, sk, h, kvh, d, dtype, causal)(
        bq, bk, interpret=interpret)


def _tuning_backend() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def _in_trace() -> bool:
    """True when called under an ambient jax trace (jit/grad/vmap).

    Measurement is impossible there: a jitted candidate invoked while an
    outer trace is active gets STAGED into that trace, so its outputs are
    tracers and the timing sync (`float(...)`) raises
    ConcretizationTypeError — which the sweep then mis-records as a
    persistent all-candidates-failed entry (observed on-chip for the
    b8 bench experiment). Dispatches under jit must fall back to the
    cache or the defaults; real sweeps run from eager dispatch sites or
    explicit pre-tuning (scripts/tpu_smoke.py)."""
    for mod in ("jax.core", "jax._src.core"):
        try:
            import importlib
            fn = getattr(importlib.import_module(mod), "trace_state_clean")
            return not fn()
        except AttributeError:
            continue
        except Exception:
            break
    # No known predicate in this jax version: assume tracing. That
    # disables implicit (measure=None) sweeps everywhere — the smoke
    # script's pre-tuning then FAILS LOUDLY (it asserts source ==
    # "measured") — which beats the silent alternative: an under-trace
    # sweep mis-persisting an all-candidates-failed entry to the shared
    # cache (the bug this guard exists for). Tests inject measure= and
    # are unaffected.
    return True


# --------------------------------------------------------------------------
# fused cross-entropy vocab-chunk tuning (same cache/policy machinery).
# The chunk trades scan length against per-chunk logits HBM: too small
# pays scan overhead, too large re-materialises what the kernel exists
# to avoid. Like cuDNN algo choice, the right point is measured, not
# guessed.
# --------------------------------------------------------------------------

CE_DEFAULT_CHUNK = 4096
CE_CANDIDATES = (1024, 2048, 4096, 8192, 16384)


def ce_candidates(vocab: int):
    """Legal vocab-chunk candidates, default first, clamped to V."""
    out = []
    for c in (CE_DEFAULT_CHUNK,) + CE_CANDIDATES:
        c = min(c, vocab)
        if c % 128 and c != vocab:   # keep lane-aligned tiles
            continue
        if c not in out:
            out.append(c)
    return out


def _ce_measurer(n, d, v, dtype):
    """Per-sweep closure: the [V, D] head (multi-GB at 100k vocab)
    materialises once, every candidate reuses it."""
    from .fused_ce import fused_cross_entropy

    rng = np.random.default_rng(0)
    x = _rand(rng, (n, d), dtype)
    head = _rand(rng, (v, d), dtype, scale=0.05)
    labels = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)

    def measure(chunk):
        f = jax.jit(jax.grad(
            lambda x, h: fused_cross_entropy(x, h, labels,
                                             vocab_chunk=chunk),
            argnums=(0, 1)))
        out = f(x, head)                 # compile + warmup
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(x, head)
            float(out[0][0, 0].astype(jnp.float32))  # axon-safe sync
            best = min(best, time.perf_counter() - t0)
        return best

    return measure


def _measure_ce(n, d, v, dtype, chunk) -> float:
    """One-shot measurement (tests); sweeps use _ce_measurer."""
    return _ce_measurer(n, d, v, dtype)(chunk)


def ce_chunk(n_tokens, hidden, vocab, dtype,
             default: int = CE_DEFAULT_CHUNK,
             measure: Optional[Callable] = None,
             cache: Optional[AutotuneCache] = None) -> int:
    """Tuned vocab_chunk for a fused-CE call; measures once per shape
    key and caches (memory + disk), same policy gates as flash_blocks."""
    default = min(default, vocab)
    key = (f"ce:{jax.default_backend()}:{jnp.dtype(dtype).name}:"
           f"n{n_tokens}v{vocab}d{hidden}")
    mode = _mode()
    if not _flags.flag_value("use_autotune") or mode == "0":
        _USED[key] = {"chunk": default, "source": "off"}
        return default
    if measure is None and mode != "cached" and not _tuning_backend():
        _USED[key] = {"chunk": default, "source": "default-not-tpu"}
        return default
    cache = cache or _CACHE
    hit = cache.get(key)
    _monitor.inc("autotune.cache.hit" if hit and not hit.get("error")
                 else "autotune.cache.miss")
    if hit and not hit.get("error"):
        _USED[key] = {"chunk": hit["chunk"], "source": "cache"}
        return int(hit["chunk"])
    if key in _FAILED_KEYS or (
            hit and hit.get("failures", 1) >= MAX_SWEEP_FAILURES):
        _USED[key] = {"chunk": default, "source": "default"}
        return default
    if mode == "cached":
        _USED[key] = {"chunk": default, "source": "default"}
        return default
    if measure is None and _in_trace():
        _USED[key] = {"chunk": default, "source": "default-in-trace"}
        return default
    cands = ce_candidates(vocab)
    if len(cands) == 1:
        cache.put(key, {"chunk": cands[0], "us": None, "candidates": 1})
        _USED[key] = {"chunk": cands[0], "source": "measured"}
        return cands[0]
    measure = measure or _ce_measurer(n_tokens, hidden, vocab, dtype)
    _monitor.inc("autotune.sweeps", doc="candidate measurement sweeps run")
    timings = {}
    last_err = None
    for c in cands:
        try:
            timings[c] = measure(c)
        except Exception as e:
            last_err = f"{type(e).__name__}: {e}"[:200]
            continue
    if not timings:
        _FAILED_KEYS.add(key)
        prior = hit.get("failures", 1) if hit and hit.get("error") else 0
        cache.put(key, {"chunk": default, "us": None, "candidates": 0,
                        "failures": prior + 1,
                        "error": f"all candidates failed ({last_err})"})
        _USED[key] = {"chunk": default, "source": "default"}
        return default
    best = min(timings, key=timings.get)
    cache.put(key, {"chunk": best, "us": round(timings[best] * 1e6, 1),
                    "candidates": len(timings),
                    "timings_us": {str(c): round(t * 1e6, 1)
                                   for c, t in timings.items()}})
    _USED[key] = {"chunk": best, "source": "measured"}
    return best


# --------------------------------------------------------------------------
# paged-attention page-size tuning (same cache/policy machinery). The page
# is the KV block the ragged decode kernel processes per grid step: small
# pages waste less pool memory on ragged tails but pay more grid steps
# and DMA descriptors per token; large pages amortise the DMA but strand
# capacity. Like the flash blocks, the right point is measured on the
# real chip, not guessed.
# --------------------------------------------------------------------------

PAGED_DEFAULT_PAGE = 16
PAGED_CANDIDATES = (8, 16, 32, 64)


def paged_candidates(dtype, max_len: int, kv_quant: bool = False):
    """Legal page-size candidates for a pool dtype, default first; the
    packed-dtype sublane tile (16) floors bf16 pages. A quantized pool
    stores int8 codes whose sublane tile is 32 rows — smaller pages
    would force the kernel arm to fall back, so they are not offered."""
    sub = 32 if kv_quant else (16 if jnp.dtype(dtype).itemsize == 2
                               else 8)
    out = []
    for ps in (PAGED_DEFAULT_PAGE,) + PAGED_CANDIDATES:
        if ps < sub or ps > max(max_len, sub):
            continue
        if ps not in out:
            out.append(ps)
    return out or [max(sub, PAGED_DEFAULT_PAGE)]


def _paged_measurer(batch, nh, kvh, d, max_len, dtype, kv_quant=False):
    """Per-sweep closure: one random KV working set, re-paged per
    candidate (pool bytes are identical across candidates; ``max_len``
    rounds up to the largest candidate so every page size divides it).
    ``kv_quant`` measures the int8-page arm: codes + per-page scales,
    quantized from the same working set."""
    from .paged_attention import ragged_paged_attention

    cap = max(PAGED_CANDIDATES)
    max_len = -(-max_len // cap) * cap
    rng = np.random.default_rng(0)
    q = _rand(rng, (batch, nh, d), dtype)
    flat_k = _rand(rng, (batch * max_len, kvh, d), dtype)
    flat_v = _rand(rng, (batch * max_len, kvh, d), dtype)
    lengths = jnp.asarray(
        rng.integers(max_len // 4, max_len + 1, (batch,)), jnp.int32)

    def _quantize(pages_arr):
        s = jnp.max(jnp.abs(pages_arr.astype(jnp.float32)),
                    axis=(2, 3)) / 127.0
        codes = jnp.round(
            pages_arr.astype(jnp.float32)
            / jnp.maximum(s, 1e-10)[:, :, None, None]).astype(jnp.int8)
        return codes, s

    def measure(ps):
        maxp = max_len // ps
        pages = batch * maxp
        kp = jnp.moveaxis(flat_k.reshape(pages, ps, kvh, d), 2, 1)
        vp = jnp.moveaxis(flat_v.reshape(pages, ps, kvh, d), 2, 1)
        bt = jnp.asarray(np.arange(pages).reshape(batch, maxp), jnp.int32)
        if kv_quant:
            kp, ks = _quantize(kp)
            vp, vs = _quantize(vp)
            f = jax.jit(lambda q_, k_, v_: ragged_paged_attention(
                q_, k_, v_, bt, lengths, k_scales=ks, v_scales=vs,
                interpret=False))
        else:
            f = jax.jit(lambda q_, k_, v_: ragged_paged_attention(
                q_, k_, v_, bt, lengths, interpret=False))
        out = f(q, kp, vp)              # compile + warmup
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(q, kp, vp)
            float(out[0, 0, 0].astype(jnp.float32))  # axon-safe sync
            best = min(best, time.perf_counter() - t0)
        return best

    return measure


def paged_page_size(batch, num_heads, kv_heads, head_dim, max_len, dtype,
                    default: int = PAGED_DEFAULT_PAGE,
                    measure: Optional[Callable] = None,
                    cache: Optional[AutotuneCache] = None,
                    kv_quant: bool = False) -> int:
    """Tuned KV page size for a paged serving shape; measures the decode
    kernel once per shape key and caches (memory + disk), same policy
    gates as flash_blocks/ce_chunk. Used by the serving engine when
    constructed with ``page_size=None``.

    ``kv_quant`` selects the int8-page arm: its own ``:kvq`` key suffix
    (the trade-off differs — int8 pages carry a 32-row sublane tile and
    a scale-plane SMEM fetch — so quantized and full-precision tunings
    never collide) and quantized measurement operands. Cold shapes that
    cannot measure (off-TPU, cached-only mode, under a trace) warm-start
    from the nearest tuned neighbor in the same key family instead of
    the hardcoded default."""
    cands = paged_candidates(dtype, max_len, kv_quant=kv_quant)
    default = default if default in cands else cands[0]
    key = (f"paged:{jax.default_backend()}:{jnp.dtype(dtype).name}:"
           f"b{batch}h{num_heads}kv{kv_heads}d{head_dim}:m{max_len}"
           + (":kvq" if kv_quant else ""))
    mode = _mode()

    def _warm_start(tag):
        nb = (cache or _CACHE).get_nearest(key)
        if nb and int(nb[1].get("page_size", -1)) in cands:
            _USED[key] = {"page_size": int(nb[1]["page_size"]),
                          "source": f"warm-start:{nb[0]}"}
            return int(nb[1]["page_size"])
        _USED[key] = {"page_size": default, "source": tag}
        return default

    if not _flags.flag_value("use_autotune") or mode == "0":
        _USED[key] = {"page_size": default, "source": "off"}
        return default
    if measure is None and mode != "cached" and not _tuning_backend():
        return _warm_start("default-not-tpu")
    cache = cache or _CACHE
    hit = cache.get(key)
    _monitor.inc("autotune.cache.hit" if hit and not hit.get("error")
                 else "autotune.cache.miss")
    if hit and not hit.get("error"):
        _USED[key] = {"page_size": hit["page_size"], "source": "cache"}
        return int(hit["page_size"])
    if key in _FAILED_KEYS or (
            hit and hit.get("failures", 1) >= MAX_SWEEP_FAILURES):
        _USED[key] = {"page_size": default, "source": "default"}
        return default
    if mode == "cached":
        return _warm_start("default")
    if measure is None and _in_trace():
        return _warm_start("default-in-trace")
    if len(cands) == 1:
        cache.put(key, {"page_size": cands[0], "us": None, "candidates": 1})
        _USED[key] = {"page_size": cands[0], "source": "measured"}
        return cands[0]
    measure = measure or _paged_measurer(batch, num_heads, kv_heads,
                                         head_dim, max_len, dtype,
                                         kv_quant=kv_quant)
    _monitor.inc("autotune.sweeps", doc="candidate measurement sweeps run")
    timings = {}
    last_err = None
    for ps in cands:
        try:
            timings[ps] = measure(ps)
        except Exception as e:
            last_err = f"{type(e).__name__}: {e}"[:200]
            continue
    if not timings:
        _FAILED_KEYS.add(key)
        prior = hit.get("failures", 1) if hit and hit.get("error") else 0
        cache.put(key, {"page_size": default, "us": None, "candidates": 0,
                        "failures": prior + 1,
                        "error": f"all candidates failed ({last_err})"})
        _USED[key] = {"page_size": default, "source": "default"}
        return default
    best = min(timings, key=timings.get)
    cache.put(key, {"page_size": best, "us": round(timings[best] * 1e6, 1),
                    "candidates": len(timings),
                    "timings_us": {str(ps): round(t * 1e6, 1)
                                   for ps, t in timings.items()}})
    _USED[key] = {"page_size": best, "source": "measured"}
    return best


# --------------------------------------------------------------------------
# segment-masked (sequence-packed) flash block tuning: same cache/policy
# machinery as flash_blocks under its own "varlen" key space — the
# segment kernel's block trade-off differs from the dense kernel's (the
# skip predicate's hit rate depends on block size vs document length),
# so the two knobs tune independently.
# --------------------------------------------------------------------------

def varlen_candidates(b, bh, sq, sk, d, dtype):
    """Legal (block_q, block_k) candidates for the segment kernels:
    flash legality plus the segment-array specs (k-side lane rule)."""
    from .tiling import segment_specs_legal

    out = []
    for bq, bk in flash_candidates(bh, sq, sk, d, dtype):
        if segment_specs_legal(b, sq, sk, bq, bk):
            out.append((bq, bk))
    if not out:
        out.append((min(DEFAULT_BLOCKS[0], sq), min(DEFAULT_BLOCKS[1], sk)))
    return out


def _varlen_measurer(b, sq, sk, h, kvh, d, dtype, causal):
    """Per-sweep closure for the segment kernel: operands (including a
    deterministic mixed-length packed segment layout — roughly
    doc ~ S/4, the regime the packed bench runs) materialise once."""
    from .flash_attention import flash_attention_segments

    rng = np.random.default_rng(0)
    q = _rand(rng, (b, sq, h, d), dtype)
    k = _rand(rng, (b, sk, kvh, d), dtype)
    v = _rand(rng, (b, sk, kvh, d), dtype)

    def layout(s):
        seg = np.full((b, s), -1, np.int32)
        pos = np.zeros((b, s), np.int32)
        for r in range(b):
            o = i = 0
            while o < s:
                ln = min(int(rng.integers(s // 8, s // 2)), s - o)
                seg[r, o:o + ln] = i
                pos[r, o:o + ln] = np.arange(ln)
                o += ln
                i += 1
        return jnp.asarray(seg), jnp.asarray(pos)

    seg_q, pos_q = layout(sq)
    seg_k, pos_k = (seg_q, pos_q) if sk == sq else layout(sk)

    def measure(bq, bk, interpret=False):
        def loss(q, k, v):
            return jnp.sum(flash_attention_segments(
                q, k, v, seg_q, seg_k, pos_q, pos_k, causal=causal,
                block_q=bq, block_k=bk,
                interpret=interpret).astype(jnp.float32))

        f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        out = f(q, k, v)                # compile + warmup
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(q, k, v)
            float(out[0][0, 0, 0, 0].astype(jnp.float32))  # axon-safe sync
            best = min(best, time.perf_counter() - t0)
        return best

    return measure


def varlen_blocks(q_shape, k_shape, dtype, causal,
                  measure: Optional[Callable] = None,
                  cache: Optional[AutotuneCache] = None):
    """Tuned (block_q, block_k) for a segment-masked flash call;
    measures once per shape key and caches (memory + disk), same policy
    gates as flash_blocks. The key rides its own ``varlen:`` prefix so
    dense and packed tunings never collide."""
    b, sq, h, d = q_shape
    sk, kvh = k_shape[1], k_shape[2]
    defaults = (min(DEFAULT_BLOCKS[0], sq), min(DEFAULT_BLOCKS[1], sk))
    key = (f"varlen:{jax.default_backend()}:{jnp.dtype(dtype).name}:"
           f"b{b}h{h}kv{kvh}:q{sq}k{sk}d{d}:c{int(bool(causal))}")
    mode = _mode()
    if not _flags.flag_value("use_autotune") or mode == "0":
        _USED[key] = {"blocks": list(defaults), "source": "off"}
        return defaults
    if measure is None and mode != "cached" and not _tuning_backend():
        _USED[key] = {"blocks": list(defaults), "source": "default-not-tpu"}
        return defaults
    cache = cache or _CACHE
    hit = cache.get(key)
    _monitor.inc("autotune.cache.hit" if hit and not hit.get("error")
                 else "autotune.cache.miss")
    if hit and not hit.get("error"):
        _USED[key] = {"blocks": list(hit["blocks"]), "source": "cache"}
        return tuple(hit["blocks"])
    if key in _FAILED_KEYS or (
            hit and hit.get("failures", 1) >= MAX_SWEEP_FAILURES):
        _USED[key] = {"blocks": list(defaults), "source": "default"}
        return defaults
    if mode == "cached":
        _USED[key] = {"blocks": list(defaults), "source": "default"}
        return defaults
    if measure is None and _in_trace():
        _USED[key] = {"blocks": list(defaults), "source": "default-in-trace"}
        return defaults
    cands = varlen_candidates(b, b * h, sq, sk, d, dtype)
    if len(cands) == 1:
        cache.put(key, {"blocks": list(cands[0]), "us": None,
                        "candidates": 1})
        _USED[key] = {"blocks": list(cands[0]), "source": "measured"}
        return cands[0]
    measure = measure or _varlen_measurer(b, sq, sk, h, kvh, d, dtype,
                                          causal)
    _monitor.inc("autotune.sweeps", doc="candidate measurement sweeps run")
    timings = {}
    last_err = None
    for bq, bk in cands:
        try:
            timings[(bq, bk)] = measure(bq, bk)
        except Exception as e:
            last_err = f"{type(e).__name__}: {e}"[:200]
            continue
    if not timings:
        _FAILED_KEYS.add(key)
        prior = hit.get("failures", 1) if hit and hit.get("error") else 0
        cache.put(key, {"blocks": list(defaults), "us": None,
                        "candidates": 0, "failures": prior + 1,
                        "error": f"all candidates failed ({last_err})"})
        _USED[key] = {"blocks": list(defaults), "source": "default"}
        return defaults
    best = min(timings, key=timings.get)
    cache.put(key, {"blocks": list(best),
                    "us": round(timings[best] * 1e6, 1),
                    "candidates": len(timings),
                    "timings_us": {f"{a}x{c}": round(t * 1e6, 1)
                                   for (a, c), t in timings.items()}})
    _USED[key] = {"blocks": list(best), "source": "measured"}
    return best


def flash_blocks(q_shape, k_shape, dtype, causal,
                 measure: Optional[Callable] = None,
                 cache: Optional[AutotuneCache] = None):
    """Tuned (block_q, block_k) for a flash call; measures once per shape
    key and caches (memory + disk). ``measure``/``cache`` are injectable
    for tests. Returns the defaults without measuring when autotune is
    off or the backend isn't a real TPU."""
    b, sq, h, d = q_shape
    sk, kvh = k_shape[1], k_shape[2]
    defaults = (min(DEFAULT_BLOCKS[0], sq), min(DEFAULT_BLOCKS[1], sk))
    key = (f"flash:{jax.default_backend()}:{jnp.dtype(dtype).name}:"
           f"b{b}h{h}kv{kvh}:q{sq}k{sk}d{d}:c{int(bool(causal))}")
    mode = _mode()
    if not _flags.flag_value("use_autotune") or mode == "0":
        _USED[key] = {"blocks": list(defaults), "source": "off"}
        return defaults
    if measure is None and mode != "cached" and not _tuning_backend():
        _USED[key] = {"blocks": list(defaults), "source": "default-not-tpu"}
        return defaults
    cache = cache or _CACHE
    hit = cache.get(key)
    _monitor.inc("autotune.cache.hit" if hit and not hit.get("error")
                 else "autotune.cache.miss")
    if hit and not hit.get("error"):
        _USED[key] = {"blocks": list(hit["blocks"]), "source": "cache"}
        return tuple(hit["blocks"])
    if key in _FAILED_KEYS or (
            hit and hit.get("failures", 1) >= MAX_SWEEP_FAILURES):
        # swept-and-failed this process, or enough OTHER processes paid
        # the failed sweep already — stop re-paying minutes of compiles
        _USED[key] = {"blocks": list(defaults), "source": "default"}
        return defaults
    if mode == "cached":   # never measure in this mode — cache miss ->
        _USED[key] = {"blocks": list(defaults), "source": "default"}
        return defaults    # known-good defaults
    if measure is None and _in_trace():
        _USED[key] = {"blocks": list(defaults), "source": "default-in-trace"}
        return defaults
    cands = flash_candidates(b * h, sq, sk, d, dtype)
    if len(cands) == 1:
        cache.put(key, {"blocks": list(cands[0]), "us": None,
                        "candidates": 1})
        _USED[key] = {"blocks": list(cands[0]), "source": "measured"}
        return cands[0]
    measure = measure or _flash_measurer(b, sq, sk, h, kvh, d, dtype,
                                         causal)
    _monitor.inc("autotune.sweeps", doc="candidate measurement sweeps run")
    timings = {}
    last_err = None
    for bq, bk in cands:
        try:
            timings[(bq, bk)] = measure(bq, bk)
        except Exception as e:   # a failing candidate just drops out
            last_err = f"{type(e).__name__}: {e}"[:200]
            continue
    if not timings:
        # record the failure for diagnosis (honoured as a hit only after
        # MAX_SWEEP_FAILURES distinct processes re-paid the sweep — a
        # transient tunnel death must not pin defaults forever, a real
        # lowering limit must not cost every process minutes) and pin
        # this process to the defaults so retraces don't re-sweep
        _FAILED_KEYS.add(key)
        prior = hit.get("failures", 1) if hit and hit.get("error") else 0
        cache.put(key, {"blocks": list(defaults), "us": None,
                        "candidates": 0, "failures": prior + 1,
                        "error": f"all candidates failed ({last_err})"})
        _USED[key] = {"blocks": list(defaults), "source": "default"}
        return defaults
    best = min(timings, key=timings.get)
    cache.put(key, {"blocks": list(best),
                    "us": round(timings[best] * 1e6, 1),
                    "candidates": len(timings),
                    "timings_us": {f"{a}x{c}": round(t * 1e6, 1)
                                   for (a, c), t in timings.items()}})
    _USED[key] = {"blocks": list(best), "source": "measured"}
    return best
