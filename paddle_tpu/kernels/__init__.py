"""Pallas TPU kernel library — the phi/kernels/fusion equivalent.

Reference capability: paddle/phi/kernels/fusion/ (52 fused CUDA kernels) and
the flash-attn wrapper (gpu/flash_attn_kernel.cu). TPU-native: hand-written
pallas kernels for the ops where XLA's automatic fusion is not enough —
flash attention (tiled online softmax on the MXU) and fused RMSNorm; the
rest of the reference's fused set (bias+act, rope, swiglu) is left to XLA
fusion, which already emits single kernels for those elementwise chains.

Dispatch mirrors the reference's KernelFactory choice (SURVEY.md §7
"KernelFactory dispatch" row): `register()` installs the pallas impls into
the functional seams (attention._FLASH_IMPL, norm._FUSED_RMS_IMPL) with
shape-support guards and XLA fallback. On TPU the kernels compile natively;
off-TPU they run in pallas interpret mode (tests) or fall back.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import flash_attention as _fa
from . import fused_ce as _fce
from . import paged_attention as _pa
from . import rms_norm as _rn
from .ring_attention import ring_attention  # noqa

flash_attention = _fa.flash_attention
flash_attention_segments = _fa.flash_attention_segments
segment_attention_ref = _fa.segment_attention_ref
count_skipped_blocks = _fa.count_skipped_blocks
fused_rms_norm = _rn.rms_norm
fused_cross_entropy = _fce.fused_cross_entropy
ragged_paged_attention = _pa.ragged_paged_attention
paged_attention_ref = _pa.paged_attention_ref

__all__ = ["flash_attention", "fused_rms_norm", "fused_cross_entropy",
           "dispatched_fused_ce", "ring_attention",
           "ragged_paged_attention", "paged_attention_ref",
           "dispatched_paged_attention",
           "flash_attention_segments", "segment_attention_ref",
           "count_skipped_blocks", "dispatched_segment_attention",
           "register", "unregister", "dispatch_stats", "reset_dispatch_stats"]

# Trace-time dispatch counters (reference capability: the KernelFactory's
# selected-kernel visibility / FLAGS_enable_api_kernel_fallback logging,
# kernel_factory.cc:230). Incremented when the dispatcher traces the pallas
# kernel vs the XLA fallback into a program — lets benchmarks *assert* the
# fast path actually engaged at their shapes instead of silently falling
# back (a silent `supported()` miss would quietly cost MFU).
_DISPATCH_STATS = {"flash": 0, "flash_fallback": 0,
                   "rms": 0, "rms_fallback": 0,
                   "fused_ce": 0, "fused_ce_fallback": 0,
                   "paged": 0, "paged_fallback": 0,
                   "paged_quant": 0, "paged_quant_fallback": 0,
                   "varlen": 0, "varlen_fallback": 0}


def dispatch_stats() -> dict:
    return dict(_DISPATCH_STATS)


def reset_dispatch_stats() -> None:
    for k in _DISPATCH_STATS:
        _DISPATCH_STATS[k] = 0


def _on_tpu() -> bool:
    # "axon" is the shared-TPU tunnel backend (a real TPU chip behind a
    # remote-compile proxy) — pallas lowers there too.
    if jax.default_backend() in ("tpu", "axon"):
        return True
    try:
        return "TPU" in (jax.devices()[0].device_kind or "")
    except Exception:
        return False


def _make_flash_dispatch(tpu_only: bool):
    def dispatch(q, k, v, *, causal=False, scale=None):
        from ..nn.functional import attention as _att
        if (tpu_only and not _on_tpu()) or not _fa.supported(q, k, v):
            _DISPATCH_STATS["flash_fallback"] += 1
            return _att.sdpa_reference(q, k, v, causal=causal, scale=scale)
        _DISPATCH_STATS["flash"] += 1
        # shapes are static at trace time -> per-shape tuned block sizes
        # (measured once, cached to disk; defaults off-TPU)
        from . import autotune as _at
        bq, bk = _at.flash_blocks(q.shape, k.shape, q.dtype, causal)
        return _fa.flash_attention(q, k, v, causal=causal, scale=scale,
                                   block_q=bq, block_k=bk)
    return dispatch


def _make_rms_dispatch(tpu_only: bool):
    def dispatch(x, w, eps):
        out_dtype = jnp.result_type(x.dtype, w.dtype)
        if ((tpu_only and not _on_tpu())
                or w.ndim != 1 or w.shape[0] != x.shape[-1]):
            # XLA path (same math as nn.functional.norm.rms_norm body)
            _DISPATCH_STATS["rms_fallback"] += 1
            xf = x.astype(jnp.float32)
            r = jax.lax.rsqrt(
                jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
            return ((xf * r).astype(x.dtype) * w).astype(out_dtype)
        _DISPATCH_STATS["rms"] += 1
        return _rn.rms_norm(x, w, eps).astype(out_dtype)
    return dispatch


def dispatched_fused_ce(x, head, labels, *, vocab_chunk=None,
                        reduction="mean", ignore_index=-100):
    """Blockwise CE with the same counter discipline as flash/rms: the
    trace records whether the memory-efficient path engaged, and an
    unsupported shape falls back to the materialising xent (identical
    math, including ignore_index masking and valid-count mean) instead
    of erroring. Works on every backend (it is pure jnp/lax, not
    pallas), so there is no tpu_only gate.

    ``vocab_chunk=None`` (default) resolves through the autotune cache;
    an explicit int is ALWAYS respected verbatim — a user capping
    loss-path HBM must not be overridden by a throughput-tuned winner."""
    if _fce.supported(x, head, labels):
        _DISPATCH_STATS["fused_ce"] += 1
        if vocab_chunk is None:
            from . import autotune as _at

            n_tokens = 1
            for s in x.shape[:-1]:
                n_tokens *= int(s)
            vocab_chunk = _at.ce_chunk(n_tokens, int(x.shape[-1]),
                                       int(head.shape[0]), x.dtype)
        return _fce.fused_cross_entropy(
            x, head, labels, vocab_chunk=vocab_chunk, reduction=reduction,
            ignore_index=ignore_index)
    _DISPATCH_STATS["fused_ce_fallback"] += 1
    logits = jnp.einsum("...d,vd->...v", x, head,
                        preferred_element_type=jnp.float32)
    return _fce.masked_xent_from_logits(
        logits, labels, ignore_index=ignore_index, reduction=reduction)


def dispatched_segment_attention(q, k, v, seg_q, seg_k, pos_q, pos_k, *,
                                 causal=False, scale=None):
    """Segment-masked (sequence-packed) attention with the same counter
    discipline as flash/paged: the Pallas segment kernel on TPU when the
    shapes are supported (block sizes resolved through the autotune
    cache's ``varlen`` knob), the pure-jnp grouped-GQA reference
    elsewhere (tier-1's CPU path). Both share one masking definition —
    packed-vs-unpacked training parity holds on either path."""
    # default-block support check BEFORE tuning (the dense dispatcher's
    # order): a shape the kernel can never run must not pay a
    # varlen_blocks measurement sweep just to fall back
    if _on_tpu() and _fa.segments_supported(q, k):
        from . import autotune as _at
        bq, bk = _at.varlen_blocks(q.shape, k.shape, q.dtype, causal)
        if _fa.segments_supported(q, k, block_q=bq, block_k=bk):
            _DISPATCH_STATS["varlen"] += 1
            return _fa.flash_attention_segments(
                q, k, v, seg_q, seg_k, pos_q, pos_k, causal=causal,
                scale=scale, block_q=bq, block_k=bk)
    _DISPATCH_STATS["varlen_fallback"] += 1
    return _fa.segment_attention_ref(q, k, v, seg_q, seg_k, pos_q, pos_k,
                                     causal=causal, scale=scale)


def dispatched_paged_attention(q, k_pages, v_pages, block_tables, lengths,
                               *, scale=None, k_scales=None,
                               v_scales=None):
    """Ragged paged decode attention with the same counter discipline as
    flash/rms: the pallas kernel on TPU when the shapes are supported,
    the pure-jnp gather reference elsewhere (tier-1's CPU path). Both
    share one masking/softmax definition — the serving engine's
    paged-vs-ring parity holds on either path.

    The kv-dtype arm (FLAGS_serving_kv_quant): int8 page pools arrive
    with per-page per-kv-head f32 ``k_scales``/``v_scales`` [P, kv]
    planes; both the kernel and the reference dequantize inline (page
    DMA stays int8, the scale folds into the attention dot), counted
    separately (``paged_quant[_fallback]``) so benchmarks can assert
    which arm a quantized shape actually traced."""
    quant = k_scales is not None
    arm = "paged_quant" if quant else "paged"
    if _on_tpu() and _pa.supported(q, k_pages, block_tables,
                                   quant=quant):
        _DISPATCH_STATS[arm] += 1
        return _pa.ragged_paged_attention(
            q, k_pages, v_pages, block_tables, lengths, scale=scale,
            k_scales=k_scales, v_scales=v_scales, interpret=False)
    _DISPATCH_STATS[arm + "_fallback"] += 1
    return _pa.paged_attention_ref(
        q, k_pages, v_pages, block_tables, lengths, scale=scale,
        k_scales=k_scales, v_scales=v_scales)


def register(flash: bool = True, rms: bool = True, tpu_only: bool = False):
    """Install pallas kernels into the op-dispatch seams.

    ``tpu_only=True`` installs lazy dispatchers that check the backend at
    call time (never at import — multi-host jax.distributed.initialize and
    platform selection must be able to run first) and fall back to the XLA
    math off-TPU."""
    from ..nn.functional import attention as _att
    from ..nn.functional import norm as _norm
    if flash:
        _att.register_flash_impl(_make_flash_dispatch(tpu_only))
        # the segment (sequence-packed) dispatcher self-gates on the
        # backend + shape support, so one registration serves both modes
        _att.register_segment_impl(dispatched_segment_attention)
    if rms:
        _norm.register_rms_impl(_make_rms_dispatch(tpu_only))


def unregister():
    from ..nn.functional import attention as _att
    from ..nn.functional import norm as _norm
    _att.register_flash_impl(None)
    _att.register_segment_impl(None)
    _norm.register_rms_impl(None)


def auto_register():
    """Called from package init. Installs the lazy TPU-gated dispatchers —
    no backend probe happens until the first attention/norm call."""
    register(tpu_only=True)
