"""Pallas TPU flash attention (forward + backward).

Reference capability: paddle/phi/kernels/gpu/flash_attn_kernel.cu (wrapping
third_party/flashattn) and nn/functional/flash_attention.py. TPU-native
design: tiled online-softmax kernels on the MXU following the canonical
pallas TPU pattern — a (batch*heads, q_blocks, k_blocks) grid whose minor
axis iterates sequentially per core, carrying running max/denominator in
VMEM scratch; causal blocks above the diagonal are skipped (predicated),
GQA queries map to their kv head via the BlockSpec index map, and the
backward pass recomputes probabilities blockwise from the saved
log-sum-exp (no S×S materialisation anywhere).

Layouts: public API is paddle's [B, S, H, D]; kernels run on [B*H, S, D].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams as _CompilerParams

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s, *,
                scale, causal, offset, block_q, block_k, num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    # bottom-right-aligned causal (sdpa convention): row r sees cols
    # <= r + offset, offset = sk - sq
    last_ki = jnp.minimum(
        (qi + 1) * block_q - 1 + offset,
        (num_k_blocks * block_k) - 1) // block_k \
        if causal else num_k_blocks - 1

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # causal: whole block above the diagonal contributes nothing
    run = (ki * block_k <= (qi + 1) * block_q - 1 + offset) \
        if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]                                    # [bq, d]
        k = k_ref[0]                                    # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, _NEG_INF)

        m_prev = m_s[:, :1]                             # [bq, 1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_s[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)                 # fully-masked rows
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_s[:, :1] + jnp.log(l)            # [bq, 1]


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    """q: [BH, Sq, D]; k/v: [BKV, Sk, D] with BH = BKV * group."""
    bh, sq, d = q.shape
    bkv, sk, _ = k.shape
    group = bh // bkv
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)

    grid = (bh, nq, nk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_k_blocks=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g=group: (b // g, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g=group: (b // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # LSE rides a trailing singleton lane dim: Mosaic requires the
            # last two block dims be (8, 128)-divisible OR equal to the
            # array dims — (block_q, 1) over [bh, sq, 1] satisfies the
            # "equal" arm with zero padding waste (a bare (1, block_q)
            # block over [bh, sq] is illegal and killed BENCH_r02).
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, offset, block_q, block_k,
                   num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    last_ki = jnp.minimum(
        (qi + 1) * block_q - 1 + offset,
        (num_k_blocks * block_k) - 1) // block_k \
        if causal else num_k_blocks - 1

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ki * block_k <= (qi + 1) * block_q - 1 + offset) \
        if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        kk = k_ref[0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                     # lse_ref[0]: [bq, 1]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == last_ki)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    offset, block_q, block_k, num_q_blocks):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal: q blocks strictly above the diagonal see none of this k block
    run = ((qi + 1) * block_q - 1 + offset >= ki * block_k) \
        if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        kk = k_ref[0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                     # lse_ref[0]: [bq, 1]
        do = do_ref[0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(res, g, *, scale, causal, block_q, block_k, interpret):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    bkv, sk, _ = k.shape
    group = bh // bkv
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    do = g.astype(q.dtype)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [BH, Sq, 1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_k_blocks=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv computed per *query* head then group-summed to the kv head
    # (avoids cross-program races for GQA).
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_q_blocks=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, j, i, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, j, i, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if group > 1:
        dk = dk_full.reshape(bkv, group, sk, d).sum(axis=1)
        dv = dv_full.reshape(bkv, group, sk, d).sum(axis=1)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public entry (custom_vjp over [B, S, H, D])
# ---------------------------------------------------------------------------

def _reshape_in(x):
    """[B, S, H, D] -> [B*H, S, D]."""
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _reshape_out(x, b, h):
    bh, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    qr = _reshape_in(q)
    kr = _reshape_in(k)
    vr = _reshape_in(v)
    out, lse = _fwd(qr, kr, vr, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k, interpret=interpret)
    return _reshape_out(out, b, h), (qr, kr, vr, out, lse, b, h)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    qr, kr, vr, out, lse, b, h = res
    kvh = kr.shape[0] // b
    gr = _reshape_in(g)
    dq, dk, dv = _bwd((qr, kr, vr, out, lse), gr, scale=scale,
                      causal=causal, block_q=block_q, block_k=block_k,
                      interpret=interpret)
    return (_reshape_out(dq, b, h), _reshape_out(dk, b, kvh),
            _reshape_out(dv, b, kvh))


_flash.defvjp(lambda q, k, v, *a: _flash_fwd(q, k, v, *a),
              _flash_bwd)


def flash_attention(q, k, v, *, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Flash attention on [B, S, H, D] (paddle layout); supports GQA
    (fewer kv heads) and causal masking. Differentiable (custom VJP,
    flash backward). Sequence lengths must divide the block sizes —
    the dispatcher (kernels/__init__.py) falls back to the XLA path
    otherwise."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    return _flash(q, k, v, float(scale), bool(causal), bq, bk, interpret)


def supported(q, k, v, *, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Whether the kernel handles these shapes (else XLA fallback).

    Beyond divisibility, this checks Mosaic's block-shape legality for
    every BlockSpec the kernels will emit (tiling.block_legal) — interpret
    mode can't catch an illegal block, so the dispatcher must reject it
    here before a doomed pallas_call is traced (BENCH_r02's failure mode).
    """
    from .tiling import flash_specs_legal
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    return (sq % bq == 0 and sk % bk == 0 and
            bq % 8 == 0 and bk % 8 == 0 and
            h % k.shape[2] == 0 and d <= 256 and
            flash_specs_legal(b * h, sq, sk, d, bq, bk, q.dtype))


# ---------------------------------------------------------------------------
# segment-aware (sequence-packed) flash attention
#
# Packed training rows hold several documents back to back, tagged by a
# per-token segment id (-1 = padding). The kernels below fuse the
# same-segment mask (and the segment-LOCAL causal mask) into the
# online-softmax tiles, and prefetch per-block min/max segment ids /
# positions (splash-attention style, PrefetchScalarGridSpec) so a block
# pair that cannot contain any same-segment (and, when causal, any
# non-future) token pair skips its matmuls entirely — packing becomes a
# FLOPs win on top of the padding win.
# ---------------------------------------------------------------------------

# rows of the prefetched per-block stats array (int32, [6, B * stride]):
_ST_QSMIN, _ST_QSMAX, _ST_KSMIN, _ST_KSMAX, _ST_QPMAX, _ST_KPMIN = range(6)


def _seg_block_stats(seg_q, seg_k, pos_q, pos_k, block_q, block_k):
    """Per-block segment/position extrema for the skip predicate.
    seg/pos: [B, S] int32 (already block-divisible). Returns
    (stats [6, B*stride] int32, stride) with q blocks at
    ``b*stride + qi`` and k blocks at ``b*stride + ki``."""
    b, sq = seg_q.shape
    sk = seg_k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    stride = max(nq, nk)

    def pad(a):
        return jnp.pad(a, ((0, 0), (0, stride - a.shape[1])))

    qs = seg_q.reshape(b, nq, block_q)
    ks = seg_k.reshape(b, nk, block_k)
    qp = pos_q.reshape(b, nq, block_q)
    kp = pos_k.reshape(b, nk, block_k)
    stats = jnp.stack([
        pad(qs.min(-1)), pad(qs.max(-1)),
        pad(ks.min(-1)), pad(ks.max(-1)),
        pad(qp.max(-1)), pad(kp.min(-1)),
    ]).astype(jnp.int32).reshape(6, b * stride)
    return stats, stride


def _seg_run_predicate(stats_ref, qb, kb, causal):
    """Scalar block-skip predicate (reads prefetched SMEM stats).

    A (q-block, k-block) pair can contribute iff some pair of tokens
    shares a (non-padding) segment id — interval overlap of
    [max(min,0), max] is conservative for any layout and exact for
    contiguous packing — and, when causal, some k token's segment-local
    position does not exceed every q token's (min pos_k <= max pos_q:
    otherwise every same-segment pair is strictly future and masked)."""
    qsmax = stats_ref[_ST_QSMAX, qb]
    ksmax = stats_ref[_ST_KSMAX, kb]
    run = jnp.logical_and(
        jnp.logical_and(qsmax >= 0, ksmax >= 0),
        jnp.logical_and(
            jnp.maximum(stats_ref[_ST_QSMIN, qb], 0) <= ksmax,
            jnp.maximum(stats_ref[_ST_KSMIN, kb], 0) <= qsmax))
    if causal:
        run = jnp.logical_and(
            run, stats_ref[_ST_KPMIN, kb] <= stats_ref[_ST_QPMAX, qb])
    return run


def count_skipped_blocks(seg_q, seg_k, pos_q, pos_k, block_q, block_k,
                         causal):
    """(skipped, total) block pairs for one head's grid — the exact
    predicate the kernels run, computed eagerly for metrics/bench (every
    head sees the same segment layout, so the fraction is per-head
    invariant). Inputs [B, S]; block sizes must divide S."""
    seg_q = jnp.asarray(seg_q, jnp.int32)
    seg_k = jnp.asarray(seg_k, jnp.int32)
    pos_q = jnp.asarray(pos_q, jnp.int32)
    pos_k = jnp.asarray(pos_k, jnp.int32)
    b, sq = seg_q.shape
    nq, nk = sq // block_q, seg_k.shape[1] // block_k
    stats, stride = _seg_block_stats(seg_q, seg_k, pos_q, pos_k,
                                     block_q, block_k)
    st = stats.reshape(6, b, stride)
    qsmin, qsmax = st[_ST_QSMIN, :, :nq], st[_ST_QSMAX, :, :nq]
    ksmin, ksmax = st[_ST_KSMIN, :, :nk], st[_ST_KSMAX, :, :nk]
    run = ((qsmax[:, :, None] >= 0) & (ksmax[:, None, :] >= 0)
           & (jnp.maximum(qsmin, 0)[:, :, None] <= ksmax[:, None, :])
           & (jnp.maximum(ksmin, 0)[:, None, :] <= qsmax[:, :, None]))
    if causal:
        run = run & (st[_ST_KPMIN, :, None, :nk]
                     <= st[_ST_QPMAX, :, :nq, None])
    total = b * nq * nk
    return total - int(jnp.sum(run)), total


def _seg_mask(qseg_ref, kseg_ref, qpos_ref, kpos_ref, causal):
    """[bq, bk] same-segment (and causal) mask from the per-token refs:
    q side rides [bq, 1] blocks, k side [1, bk] — the compare broadcasts
    straight to the score tile shape."""
    same = jnp.logical_and(qseg_ref[0] == kseg_ref[0], qseg_ref[0] >= 0)
    if causal:
        same = jnp.logical_and(same, qpos_ref[0] >= kpos_ref[0])
    return same


def _seg_fwd_kernel(stats_ref, q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
                    qpos_ref, kpos_ref, o_ref, lse_ref, acc, m_s, l_s, *,
                    scale, causal, nh, stride, num_k_blocks):
    b = pl.program_id(0) // nh
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    run = _seg_run_predicate(stats_ref, b * stride + qi, b * stride + ki,
                             causal)

    @pl.when(run)
    def _body():
        q = q_ref[0]                                    # [bq, d]
        k = k_ref[0]                                    # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        same = _seg_mask(qseg_ref, kseg_ref, qpos_ref, kpos_ref, causal)
        s = jnp.where(same, s, _NEG_INF)

        m_prev = m_s[:, :1]                             # [bq, 1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # p masked (not just s): a fully-masked ROW has m_new = -1e30,
        # where exp(s - m_new) would be 1 per lane and corrupt l — the
        # mask keeps padding rows at l == 0 so finalize emits exact 0s
        p = jnp.where(same, jnp.exp(s - m_new), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_s[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)                 # padding rows -> 0
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_s[:, :1] + jnp.log(l)


def _seg_bwd_dq_kernel(stats_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                       delta_ref, qseg_ref, kseg_ref, qpos_ref, kpos_ref,
                       dq_ref, dq_acc, *, scale, causal, nh, stride,
                       num_k_blocks):
    b = pl.program_id(0) // nh
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _seg_run_predicate(stats_ref, b * stride + qi, b * stride + ki,
                             causal)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        kk = k_ref[0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        same = _seg_mask(qseg_ref, kseg_ref, qpos_ref, kpos_ref, causal)
        # padding rows carry lse = -1e30; exp(s - lse) there would be 1,
        # so the mask (not the -1e30 trick) must zero p
        p = jnp.where(same, jnp.exp(s - lse_ref[0]), 0.0)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _seg_bwd_dkv_kernel(stats_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, qseg_ref, kseg_ref, qpos_ref, kpos_ref,
                        dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                        nh, stride, num_q_blocks):
    b = pl.program_id(0) // nh
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _seg_run_predicate(stats_ref, b * stride + qi, b * stride + ki,
                             causal)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        kk = k_ref[0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        same = _seg_mask(qseg_ref, kseg_ref, qpos_ref, kpos_ref, causal)
        p = jnp.where(same, jnp.exp(s - lse_ref[0]), 0.0)
        do = do_ref[0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _seg_views(seg_q, seg_k, pos_q, pos_k):
    """[B, S] int arrays -> the kernel-side layouts: q side [B, Sq, 1]
    (sublane-major, the LSE-block trick), k side [B, 1, Sk]
    (lane-major)."""
    return (jnp.asarray(seg_q, jnp.int32)[:, :, None],
            jnp.asarray(seg_k, jnp.int32)[:, None, :],
            jnp.asarray(pos_q, jnp.int32)[:, :, None],
            jnp.asarray(pos_k, jnp.int32)[:, None, :])


def _seg_specs(nh, group, block_q, block_k, d):
    """The in_specs shared by all three segment kernels, in
    (q, k, v, qseg, kseg, qpos, kpos) order for the given grid layout
    where axis 1 = q blocks, axis 2 = k blocks (the dkv kernel swaps the
    index-map arguments instead)."""
    qtok = pl.BlockSpec((1, block_q, 1),
                        lambda b, i, j, s_, h=nh: (b // h, i, 0))
    ktok = pl.BlockSpec((1, 1, block_k),
                        lambda b, i, j, s_, h=nh: (b // h, 0, j))
    return [
        pl.BlockSpec((1, block_q, d), lambda b, i, j, s_: (b, i, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j, s_, g=group: (b // g, j, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j, s_, g=group: (b // g, j, 0)),
        qtok, ktok, qtok, ktok,
    ]


def _seg_fwd(q, k, v, segq, segk, posq, posk, stats, stride, nh, *, scale,
             causal, block_q, block_k, interpret):
    """q: [BH, Sq, D]; k/v: [BKV, Sk, D]; seg/pos in kernel layouts."""
    bh, sq, d = q.shape
    bkv, sk, _ = k.shape
    group = bh // bkv
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, nq, nk),
        in_specs=_seg_specs(nh, group, block_q, block_k, d),
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, s_: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j, s_: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        functools.partial(_seg_fwd_kernel, scale=scale, causal=causal,
                          nh=nh, stride=stride, num_k_blocks=nk),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(stats, q, k, v, segq, segk, posq, posk)
    return out, lse


def _seg_bwd(res, g, *, scale, causal, block_q, block_k, interpret):
    (q, k, v, out, lse, segq, segk, posq, posk, stats, stride, nh) = res
    bh, sq, d = q.shape
    bkv, sk, _ = k.shape
    group = bh // bkv
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    do = g.astype(q.dtype)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [BH, Sq, 1]

    def qrow(b, i, j, s_):
        return (b, i, 0)

    row_specs = [pl.BlockSpec((1, block_q, d), qrow),
                 pl.BlockSpec((1, block_q, 1), qrow),
                 pl.BlockSpec((1, block_q, 1), qrow)]

    dq = pl.pallas_call(
        functools.partial(_seg_bwd_dq_kernel, scale=scale, causal=causal,
                          nh=nh, stride=stride, num_k_blocks=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nq, nk),
            in_specs=(_seg_specs(nh, group, block_q, block_k, d)[:3]
                      + row_specs
                      + _seg_specs(nh, group, block_q, block_k, d)[3:]),
            out_specs=pl.BlockSpec((1, block_q, d), qrow),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(stats, q, k, v, do, lse, delta, segq, segk, posq, posk)

    # dk/dv per query head then group-summed (GQA, same as the dense bwd);
    # grid minor axis iterates q blocks, so every index map swaps (i, j)
    def swap(spec):
        im = spec.index_map
        return pl.BlockSpec(spec.block_shape,
                            lambda b, j, i, s_, f=im: f(b, i, j, s_))

    base = [swap(s) for s in _seg_specs(nh, group, block_q, block_k, d)]
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_seg_bwd_dkv_kernel, scale=scale, causal=causal,
                          nh=nh, stride=stride, num_q_blocks=nq),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nk, nq),
            in_specs=(base[:3] + [swap(s) for s in row_specs] + base[3:]),
            out_specs=[
                pl.BlockSpec((1, block_k, d),
                             lambda b, j, i, s_: (b, j, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda b, j, i, s_: (b, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(stats, q, k, v, do, lse, delta, segq, segk, posq, posk)

    if group > 1:
        dk = dk_full.reshape(bkv, group, sk, d).sum(axis=1)
        dv = dv_full.reshape(bkv, group, sk, d).sum(axis=1)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _flash_seg(q, k, v, seg_q, seg_k, pos_q, pos_k, scale, causal,
               block_q, block_k, interpret):
    out, _ = _flash_seg_fwd(q, k, v, seg_q, seg_k, pos_q, pos_k, scale,
                            causal, block_q, block_k, interpret)
    return out


def _flash_seg_fwd(q, k, v, seg_q, seg_k, pos_q, pos_k, scale, causal,
                   block_q, block_k, interpret):
    b, sq, h, d = q.shape
    qr = _reshape_in(q)
    kr = _reshape_in(k)
    vr = _reshape_in(v)
    segq, segk, posq, posk = _seg_views(seg_q, seg_k, pos_q, pos_k)
    stats, stride = _seg_block_stats(
        jnp.asarray(seg_q, jnp.int32), jnp.asarray(seg_k, jnp.int32),
        jnp.asarray(pos_q, jnp.int32), jnp.asarray(pos_k, jnp.int32),
        block_q, block_k)
    out, lse = _seg_fwd(qr, kr, vr, segq, segk, posq, posk, stats, stride,
                        h, scale=scale, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    res = (qr, kr, vr, out, lse, segq, segk, posq, posk, stats, stride, h)
    return _reshape_out(out, b, h), (res, b, h)


def _flash_seg_bwd(scale, causal, block_q, block_k, interpret, resbh, g):
    res, b, h = resbh
    kvh = res[1].shape[0] // b
    gr = _reshape_in(g)
    dq, dk, dv = _seg_bwd(res, gr, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)
    return (_reshape_out(dq, b, h), _reshape_out(dk, b, kvh),
            _reshape_out(dv, b, kvh), None, None, None, None)


_flash_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def flash_attention_segments(q, k, v, seg_q, seg_k, pos_q, pos_k, *,
                             causal=False, scale=None,
                             block_q=DEFAULT_BLOCK_Q,
                             block_k=DEFAULT_BLOCK_K, interpret=None):
    """Segment-masked flash attention on [B, S, H, D] packed rows.

    ``seg_q``/``seg_k`` [B, S] int32 tag each token with its document
    (-1 = padding: such rows produce exact zeros and zero gradients);
    tokens attend only within their own segment, and ``causal`` masks on
    the segment-LOCAL positions ``pos_q``/``pos_k`` [B, S] (for
    self-attention packing, pos = offset within the document). GQA and
    the blockwise custom-VJP backward work exactly as in the dense
    ``flash_attention``; additionally, block pairs that can contain no
    visible token pair are skipped via prefetched per-block segment /
    position extrema (see ``count_skipped_blocks`` for the predicate)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    return _flash_seg(q, k, v, jnp.asarray(seg_q, jnp.int32),
                      jnp.asarray(seg_k, jnp.int32),
                      jnp.asarray(pos_q, jnp.int32),
                      jnp.asarray(pos_k, jnp.int32),
                      float(scale), bool(causal), bq, bk, interpret)


def segment_attention_ref(q, k, v, seg_q, seg_k, pos_q, pos_k, *,
                          causal=False, scale=None):
    """Pure-jnp reference with IDENTICAL masking semantics to the
    segment kernels (tier-1's CPU path and the dispatcher fallback):
    same-segment block-diagonal mask, segment-local causal, padding
    (seg < 0) rows exactly zero. GQA contracts grouped heads directly —
    no jnp.repeat of k/v, so KV HBM traffic stays at the kv-head count."""
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    seg_q = jnp.asarray(seg_q, jnp.int32)
    seg_k = jnp.asarray(seg_k, jnp.int32)
    q5 = q.astype(jnp.float32).reshape(b, sq, kvh, g, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", q5,
                   k.astype(jnp.float32)) * scale
    same = ((seg_q[:, :, None] == seg_k[:, None, :])
            & (seg_q[:, :, None] >= 0))                  # [B, Sq, Sk]
    if causal:
        same = same & (jnp.asarray(pos_q)[:, :, None]
                       >= jnp.asarray(pos_k)[:, None, :])
    mask = same[:, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.where(mask, jnp.exp(s - m), 0.0)
    l = jnp.sum(e, axis=-1, keepdims=True)
    l = jnp.where(l == 0.0, 1.0, l)                      # padding rows -> 0
    out = jnp.einsum("bhgqk,bkhd->bqhgd", e / l,
                     v.astype(jnp.float32))
    return out.reshape(b, sq, h, d).astype(q.dtype)


def segments_supported(q, k, *, block_q=DEFAULT_BLOCK_Q,
                       block_k=DEFAULT_BLOCK_K):
    """Whether the segment kernels handle these shapes (else the
    dispatcher uses segment_attention_ref). Adds the segment-array
    BlockSpec legality (tiling.segment_specs_legal) on top of the dense
    kernel's rules — notably the k-side lane rule: block_k % 128 == 0 or
    block_k == Sk."""
    from .tiling import flash_specs_legal, segment_specs_legal
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    return (sq % bq == 0 and sk % bk == 0 and
            bq % 8 == 0 and bk % 8 == 0 and
            h % k.shape[2] == 0 and d <= 256 and
            flash_specs_legal(b * h, sq, sk, d, bq, bk, q.dtype) and
            segment_specs_legal(b, sq, sk, bq, bk))
