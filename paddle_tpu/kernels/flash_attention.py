"""Pallas TPU flash attention (forward + backward).

Reference capability: paddle/phi/kernels/gpu/flash_attn_kernel.cu (wrapping
third_party/flashattn) and nn/functional/flash_attention.py. TPU-native
design: tiled online-softmax kernels on the MXU following the canonical
pallas TPU pattern — a (batch*heads, q_blocks, k_blocks) grid whose minor
axis iterates sequentially per core, carrying running max/denominator in
VMEM scratch; causal blocks above the diagonal are skipped (predicated),
GQA queries map to their kv head via the BlockSpec index map, and the
backward pass recomputes probabilities blockwise from the saved
log-sum-exp (no S×S materialisation anywhere).

Layouts: public API is paddle's [B, S, H, D]; kernels run on [B*H, S, D].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams as _CompilerParams

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s, *,
                scale, causal, offset, block_q, block_k, num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    # bottom-right-aligned causal (sdpa convention): row r sees cols
    # <= r + offset, offset = sk - sq
    last_ki = jnp.minimum(
        (qi + 1) * block_q - 1 + offset,
        (num_k_blocks * block_k) - 1) // block_k \
        if causal else num_k_blocks - 1

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # causal: whole block above the diagonal contributes nothing
    run = (ki * block_k <= (qi + 1) * block_q - 1 + offset) \
        if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]                                    # [bq, d]
        k = k_ref[0]                                    # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, _NEG_INF)

        m_prev = m_s[:, :1]                             # [bq, 1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_s[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)                 # fully-masked rows
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_s[:, :1] + jnp.log(l)            # [bq, 1]


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    """q: [BH, Sq, D]; k/v: [BKV, Sk, D] with BH = BKV * group."""
    bh, sq, d = q.shape
    bkv, sk, _ = k.shape
    group = bh // bkv
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)

    grid = (bh, nq, nk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_k_blocks=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g=group: (b // g, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g=group: (b // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # LSE rides a trailing singleton lane dim: Mosaic requires the
            # last two block dims be (8, 128)-divisible OR equal to the
            # array dims — (block_q, 1) over [bh, sq, 1] satisfies the
            # "equal" arm with zero padding waste (a bare (1, block_q)
            # block over [bh, sq] is illegal and killed BENCH_r02).
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, offset, block_q, block_k,
                   num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    last_ki = jnp.minimum(
        (qi + 1) * block_q - 1 + offset,
        (num_k_blocks * block_k) - 1) // block_k \
        if causal else num_k_blocks - 1

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ki * block_k <= (qi + 1) * block_q - 1 + offset) \
        if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        kk = k_ref[0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                     # lse_ref[0]: [bq, 1]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == last_ki)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    offset, block_q, block_k, num_q_blocks):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal: q blocks strictly above the diagonal see none of this k block
    run = ((qi + 1) * block_q - 1 + offset >= ki * block_k) \
        if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        kk = k_ref[0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                     # lse_ref[0]: [bq, 1]
        do = do_ref[0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(res, g, *, scale, causal, block_q, block_k, interpret):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    bkv, sk, _ = k.shape
    group = bh // bkv
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    do = g.astype(q.dtype)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [BH, Sq, 1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_k_blocks=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv computed per *query* head then group-summed to the kv head
    # (avoids cross-program races for GQA).
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_q_blocks=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, j, i, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, j, i, g_=group: (b // g_, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if group > 1:
        dk = dk_full.reshape(bkv, group, sk, d).sum(axis=1)
        dv = dv_full.reshape(bkv, group, sk, d).sum(axis=1)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public entry (custom_vjp over [B, S, H, D])
# ---------------------------------------------------------------------------

def _reshape_in(x):
    """[B, S, H, D] -> [B*H, S, D]."""
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _reshape_out(x, b, h):
    bh, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    qr = _reshape_in(q)
    kr = _reshape_in(k)
    vr = _reshape_in(v)
    out, lse = _fwd(qr, kr, vr, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k, interpret=interpret)
    return _reshape_out(out, b, h), (qr, kr, vr, out, lse, b, h)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    qr, kr, vr, out, lse, b, h = res
    kvh = kr.shape[0] // b
    gr = _reshape_in(g)
    dq, dk, dv = _bwd((qr, kr, vr, out, lse), gr, scale=scale,
                      causal=causal, block_q=block_q, block_k=block_k,
                      interpret=interpret)
    return (_reshape_out(dq, b, h), _reshape_out(dk, b, kvh),
            _reshape_out(dv, b, kvh))


_flash.defvjp(lambda q, k, v, *a: _flash_fwd(q, k, v, *a),
              _flash_bwd)


def flash_attention(q, k, v, *, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Flash attention on [B, S, H, D] (paddle layout); supports GQA
    (fewer kv heads) and causal masking. Differentiable (custom VJP,
    flash backward). Sequence lengths must divide the block sizes —
    the dispatcher (kernels/__init__.py) falls back to the XLA path
    otherwise."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    return _flash(q, k, v, float(scale), bool(causal), bq, bk, interpret)


def supported(q, k, v, *, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Whether the kernel handles these shapes (else XLA fallback).

    Beyond divisibility, this checks Mosaic's block-shape legality for
    every BlockSpec the kernels will emit (tiling.block_legal) — interpret
    mode can't catch an illegal block, so the dispatcher must reject it
    here before a doomed pallas_call is traced (BENCH_r02's failure mode).
    """
    from .tiling import flash_specs_legal
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    return (sq % bq == 0 and sk % bk == 0 and
            bq % 8 == 0 and bk % 8 == 0 and
            h % k.shape[2] == 0 and d <= 256 and
            flash_specs_legal(b * h, sq, sk, d, bq, bk, q.dtype))
