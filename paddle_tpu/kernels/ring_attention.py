"""Ring attention — exact long-context attention over a sequence axis.

Reference capability: the SEP topology axis + SP utilities (SURVEY.md §5
"Long context": the reference scales sequence with SEP/SP + recompute but
has no ring/Ulysses kernels — this module *exceeds* reference parity, as
SURVEY.md §2.6 SEP row calls for).

TPU-native design: the sequence is sharded over a mesh axis ('sp'); each
device holds q/k/v chunks [B, S/n, H, D]. A `lax.scan` over n ring steps
rotates the k/v chunk with `lax.ppermute` (ICI collective-permute — the
ring rides neighbor links, overlapping comm with the chunk's attention
math) while an online-softmax accumulator (m, l, acc) merges each chunk's
contribution — flash attention across devices. Causality is enforced with
global position masks, so the result is *exactly* standard causal
attention on the full sequence. Fully differentiable (AD through the scan
reverses the ring)."""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ..core.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention"]

_NEG_INF = -1e30


def _chunk_attn(q, k, v, row0, col0, *, scale, causal):
    """One q-chunk × one kv-chunk partial attention.
    q: [B, Sq, H, D], k/v: [B, Sk, H, D] (heads already matched).
    Returns (scores_exp_sum l [B,H,Sq,1], row max m [B,H,Sq,1],
    weighted values acc [B,H,Sq,D])."""
    qt = jnp.swapaxes(q, 1, 2)          # [B,H,Sq,D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        rows = row0 + jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 0)
        cols = col0 + jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 1)
        s = jnp.where(rows[None, None] >= cols[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                    # [B,H,Sq,1]
    # guard fully-masked chunks (m = -inf): shift by 0 there
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vt.dtype), vt,
                     preferred_element_type=jnp.float32)
    return m_safe, l, acc


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                   causal: bool = True, scale: Optional[float] = None):
    """Exact attention over sequence sharded on ``axis``.

    q/k/v: [B, S, H, D] global arrays (S sharded over ``axis``); returns
    [B, S, H, D] with the same sharding. GQA supported (kv heads divide q
    heads)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]
    s_local = q.shape[1] // n
    h, kvh = q.shape[2], k.shape[2]
    group = h // kvh

    def local(qc, kc, vc):
        # qc/kc/vc: local chunks [B, S/n, H(or KV), D]
        if group > 1:
            kc = jnp.repeat(kc, group, axis=2)
            vc = jnp.repeat(vc, group, axis=2)
        idx = lax.axis_index(axis)
        my_row0 = idx * s_local

        def ring_step(carry, t):
            kck, vck, m, l, acc = carry
            # kv chunk currently held came from device (idx - t) mod n
            src = (idx - t) % n
            col0 = src * s_local
            mc, lc, ac = _chunk_attn(qc, kck, vck, my_row0, col0,
                                     scale=scale, causal=causal)
            m_new = jnp.maximum(m, mc)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(mc - m_new)
            l_new = l * alpha + lc * beta
            acc_new = acc * alpha + ac * beta
            # rotate kv to the next device (ring)
            perm = [(i, (i + 1) % n) for i in range(n)]
            kck = lax.ppermute(kck, axis, perm)
            vck = lax.ppermute(vck, axis, perm)
            return (kck, vck, m_new, l_new, acc_new), None

        b, sl = qc.shape[0], qc.shape[1]
        m0 = jnp.full((b, h, sl, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, sl, 1), jnp.float32)
        a0 = jnp.zeros((b, h, sl, qc.shape[-1]), jnp.float32)
        (_kf, _vf, m, l, acc), _ = lax.scan(
            ring_step, (kc, vc, m0, l0, a0), jnp.arange(n))
        l = jnp.where(l == 0.0, 1.0, l)
        out = (acc / l).astype(qc.dtype)
        return jnp.swapaxes(out, 1, 2)   # [B, S/n, H, D]

    spec = P(None, axis, None, None)
    return shard_map(local, mesh=mesh,
                     in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
