"""Pallas-TPU naming shims (single home for the kernels' version
compat, like core/jax_compat.py for the core jax surface)."""
from jax.experimental.pallas import tpu as pltpu

try:
    CompilerParams = pltpu.CompilerParams
except AttributeError:      # jax < 0.6 names it TPUCompilerParams
    CompilerParams = pltpu.TPUCompilerParams

__all__ = ["CompilerParams"]
