"""Pallas fused RMSNorm (forward + backward).

Reference capability: python/paddle/incubate/nn/functional/fused_rms_norm.py
(backed by phi fused kernels). TPU-native: one row-tiled kernel per pass —
a single HBM read of x produces y (and the saved rstd), instead of the
separate mean-square/normalize/scale ops; backward fuses the two reduction
terms. XLA already fuses simple norm chains well; this kernel exists for
the long-row case (hidden >= 4096) where keeping the row resident in VMEM
beats XLA's fusion, and as the pattern for further fused kernels.

Mosaic legality (see tiling.py): rstd is carried as [n, 1] — a (br, 1)
block over it hits the "equal to the array dim" arm of the tiling rule;
rank-1 (br,) blocks over a partitioned [n] array fail to lower on real
TPU (verified v5e). The backward's dw reduction accumulates into a single
(1, d) output block with a constant index map (the canonical Pallas
reduction pattern) instead of one partial row per grid step, whose
(1, d) block over [grid, d] is illegal whenever grid > 1 — the BENCH_r02
class of bug.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams as _CompilerParams

DEFAULT_BLOCK_ROWS = 256
# rows*cols budget per block: ~6 live (br, d) f32 buffers double-buffered
# must fit the ~16MB scoped-vmem limit (v5e OOMs at br=256, d=4096)
_MAX_BLOCK_ELEMS = 128 * 1024


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block_rows(block_rows, n, d):
    br = min(block_rows, n, max(8, (_MAX_BLOCK_ELEMS // d) // 8 * 8))
    while br > 8 and n % br != 0:
        br -= 8
    return br


def _fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    y_ref[:] = (x * r * w_ref[:].astype(jnp.float32)).astype(y_ref.dtype)
    rstd_ref[:] = r                                      # [br, 1]


def _bwd_kernel(x_ref, w_ref, rstd_ref, dy_ref, dx_ref, dw_ref, *, eps):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    r = rstd_ref[:]                                      # [br, 1]
    g = dy * w
    # dx = r*g - x * r^3 * mean(g*x)
    mean_gx = jnp.mean(g * x, axis=-1, keepdims=True)
    dx_ref[:] = (r * g - x * (r ** 3) * mean_gx).astype(dx_ref.dtype)
    # dw accumulates across the row grid into one resident (1, d) block
    @pl.when(i == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)
    dw_ref[:] += jnp.sum(dy * x * r, axis=0, keepdims=True)


def _rows(x):
    return x.reshape(-1, x.shape[-1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def rms_norm(x, w, eps=1e-6, block_rows=DEFAULT_BLOCK_ROWS, interpret=None):
    y, _ = _rms_fwd(x, w, eps, block_rows, interpret)
    return y


def _call_fwd(x2, w, eps, br, interpret):
    n, d = x2.shape
    grid = (pl.cdiv(n, br),)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w.reshape(1, d))


def _rms_fwd(x, w, eps, block_rows, interpret):
    if interpret is None:
        interpret = _interpret_default()
    x2 = _rows(x)
    n, d = x2.shape
    br = _pick_block_rows(block_rows, n, d)
    if n % br != 0 or br % 8 != 0:   # fallback: plain XLA path
        xf = x2.astype(jnp.float32)
        r = jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
        y = (xf * r * w.astype(jnp.float32)).astype(x.dtype)
        return y.reshape(x.shape), (x, w, r, True)
    y, rstd = _call_fwd(x2, w, eps, br, interpret)
    return y.reshape(x.shape), (x, w, rstd, interpret)


def _rms_bwd(eps, block_rows, _interp_unused, res, dy):
    x, w, rstd, interpret = res                          # rstd: [n, 1]
    x2 = _rows(x)
    dy2 = _rows(dy)
    n, d = x2.shape
    br = _pick_block_rows(block_rows, n, d)
    if n % br != 0 or br % 8 != 0:
        xf = x2.astype(jnp.float32)
        g = dy2.astype(jnp.float32) * w.astype(jnp.float32)
        r = rstd
        dx = (r * g - xf * (r ** 3)
              * jnp.mean(g * xf, -1, keepdims=True)).astype(x.dtype)
        dw = jnp.sum(dy2.astype(jnp.float32) * xf * r, axis=0)
        return dx.reshape(x.shape), dw.astype(w.dtype)
    grid = (pl.cdiv(n, br),)
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, w.reshape(1, d), rstd, dy2)
    return dx.reshape(x.shape), dw[0].astype(w.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)
