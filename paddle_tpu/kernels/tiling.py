"""Mosaic (Pallas TPU) block-shape legality rules.

The TPU lowering requires that the last two dimensions of every BlockSpec
block be divisible by the dtype's native tile — (8, 128) for 4-byte types,
(16, 128) for 2-byte, (32, 128) for 1-byte — OR equal the corresponding
dimension of the overall array. Rank-1 blocks need the last dim divisible
by 128 or equal to the array's. Interpret mode does not enforce this, so
a kernel can pass every CPU test and still fail to lower on the chip
(exactly what BENCH_r02 recorded); `block_legal` lets `supported()` and
the test suite check legality without a TPU.

Reference capability: the reference validates kernel launch configs at
dispatch time (phi KernelFactory); here legality is a pure shape predicate
so the XLA fallback can engage *before* a doomed pallas_call is traced.
"""
from __future__ import annotations

import numpy as np

_LANE = 128


def _sublane(dtype) -> int:
    itemsize = np.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


def block_legal(block_shape, array_shape, dtype=np.float32) -> bool:
    """Whether Mosaic can lower a block of ``block_shape`` (ints, or None
    for squeezed dims) over an array of ``array_shape``.

    Note: squeezed (None) dims still count toward the trailing-two rule —
    a ``(None, bq)`` block over ``[bh, sq]`` is checked as ``(1, bq)`` and
    is illegal unless ``bh == 1`` (verified empirically on TPU v5e).
    """
    block = [1 if b is None else int(b) for b in block_shape]
    array = list(array_shape)
    if len(block) != len(array):
        return False
    if any(b < 1 or b > a for b, a in zip(block, array)):
        return False
    if len(block) == 0:
        return True
    sub = _sublane(dtype)
    if len(block) == 1:
        return block[-1] % _LANE == 0 or block[-1] == array[-1]
    ok_lane = block[-1] % _LANE == 0 or block[-1] == array[-1]
    ok_sub = block[-2] % sub == 0 or block[-2] == array[-2]
    return ok_lane and ok_sub


def flash_specs_legal(bh, sq, sk, d, block_q, block_k, dtype) -> bool:
    """Legality of every BlockSpec the flash kernels emit (fwd + bwd)."""
    lse = np.float32
    return (
        # q/o/do/dq blocks: (1, block_q, d) over [bh, s, d]
        block_legal((1, block_q, d), (bh, sq, d), dtype)
        # k/v/dk/dv blocks: (1, block_k, d)
        and block_legal((1, block_k, d), (bh, sk, d), dtype)
        # lse/delta blocks: (1, block_q, 1) over [bh, sq, 1] (always f32)
        and block_legal((1, block_q, 1), (bh, sq, 1), lse)
    )


def segment_specs_legal(b, sq, sk, block_q, block_k) -> bool:
    """Legality of the EXTRA BlockSpecs the segment-aware flash kernels
    add on top of flash_specs_legal: per-token segment-id / position
    arrays in the trailing-singleton layout (q side ``[B, Sq, 1]`` with
    (1, block_q, 1) blocks — the LSE trick) and the lane-major k side
    (``[B, 1, Sk]`` with (1, 1, block_k) blocks, whose last dim must hit
    the 128-lane rule or equal Sk). All int32."""
    i32 = np.int32
    return (block_legal((1, block_q, 1), (b, sq, 1), i32)
            and block_legal((1, 1, block_k), (b, 1, sk), i32))
