"""Blockwise (memory-efficient) softmax cross-entropy for the LM head.

Reference capability: the fused cross-entropy hot path —
paddle/phi/kernels/gpu/cross_entropy_kernel.cu (softmax+xent in one pass)
and python/paddle/nn/functional/loss.py:2110 margin_cross_entropy's
dedicated kernel route. There the fusion saves a softmax round-trip; here
the win is bigger: the [B*S, V] logits tensor NEVER exists in HBM.

TPU-native design (NOT a port): a `lax.scan` over vocabulary chunks.

- forward: for each chunk of the head matrix, one [N, D] x [D, Vb] matmul
  (rides the MXU in bf16, f32 accumulation) feeds an online-softmax
  update (running max `m`, running sum-of-exp `s`, gathered gold logit),
  the same recurrence the flash-attention kernel uses along K. Peak HBM
  for the loss is O(N * Vb) instead of O(N * V).
- backward: custom_vjp recomputes each logit chunk (rematerialisation —
  trade one extra matmul pass for never storing softmax), forms
  d_logits = (softmax - onehot) * g on the fly, and contracts it
  immediately into dx and the chunk's dhead rows.

FLOPs: 8*N*D*V vs 6*N*D*V for the materialising path (+1 matmul pass in
bwd); HBM traffic for the head drops from ~3 reads/writes of [N, V] f32
to zero. At Llama shapes (V = 32k-128k) the loss path is HBM-bound, so
this is a net win on TPU — and it makes vocab sizes that previously
OOM'd (128k at 16G HBM) feasible.

Chunking is over the STATIC vocab axis, so everything stays
fixed-shape for XLA; the chunk count is `ceil(V / vocab_chunk)` with the
tail chunk masked, never a dynamic shape.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_cross_entropy", "masked_xent_from_logits", "supported"]

_NEG = -1e30   # large-negative instead of -inf: keeps XLA's max/exp exact
               # for masked lanes without generating inf-inf = nan paths


def masked_xent_from_logits(logits, labels, *, ignore_index: int = -100,
                            reduction: str = "mean"):
    """Materialising xent with the SAME ignore_index semantics as the
    blockwise kernel: ignored / out-of-range labels contribute zero loss
    (and zero gradient), ``mean`` divides by the valid count. The one
    shared definition for every logits-in-HBM call site (dispatcher
    fallback, multi-device llama loss) so the semantics cannot diverge."""
    v = logits.shape[-1]
    valid = (labels != ignore_index) & (labels >= 0) & (labels < v)
    safe = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    per = jnp.where(valid, logz - gold, 0.0)
    if reduction == "mean":
        return jnp.sum(per) / jnp.maximum(
            jnp.sum(valid.astype(per.dtype)), 1.0)
    if reduction == "sum":
        return jnp.sum(per)
    return per


def supported(x, head, labels) -> bool:
    """Shape guard for the dispatcher: 2D-flattenable x, matching head."""
    return (x.ndim >= 2 and head.ndim == 2
            and x.shape[-1] == head.shape[-1]
            and labels.shape == x.shape[:-1])


def _pad_head(head, vocab_chunk):
    v = head.shape[0]
    k = -(-v // vocab_chunk)            # ceil
    pad = k * vocab_chunk - v
    if pad:
        head = jnp.pad(head, ((0, pad), (0, 0)))
    return head.reshape(k, vocab_chunk, head.shape[-1]), v


def _chunk_logits(x, head_chunk, base, valid_v):
    """[N, Vb] f32 logits for one head chunk, padded rows masked."""
    logits = jnp.einsum("nd,vd->nv", x, head_chunk,
                        preferred_element_type=jnp.float32)
    vb = head_chunk.shape[0]
    col = base + jnp.arange(vb)
    return jnp.where(col[None, :] < valid_v, logits, _NEG)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _blockwise_ce(x, headc, labels, valid_v):
    """Per-token CE loss [N] from x [N, D], headc [K, Vb, D], labels [N]."""
    loss, _ = _blockwise_ce_fwd(x, headc, labels, valid_v)
    return loss


def _blockwise_ce_fwd(x, headc, labels, valid_v):
    n = x.shape[0]
    k, vb, _ = headc.shape

    def body(carry, inp):
        m, s, gold = carry
        i, hc = inp
        base = i * vb
        logits = _chunk_logits(x, hc, base, valid_v)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = labels - base
        in_chunk = (local >= 0) & (local < vb)
        gl = jnp.take_along_axis(
            logits, jnp.clip(local, 0, vb - 1)[:, None], axis=-1)[:, 0]
        gold = jnp.where(in_chunk, gl, gold)
        return (m_new, s, gold), None

    init = (jnp.full((n,), _NEG, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.full((n,), _NEG, jnp.float32))
    (m, s, gold), _ = lax.scan(body, init, (jnp.arange(k), headc))
    lse = m + jnp.log(s)
    loss = lse - gold
    return loss, (x, headc, labels, lse)


def _blockwise_ce_bwd(valid_v, res, g):
    x, headc, labels, lse = res
    k, vb, d = headc.shape

    def body(dx, inp):
        i, hc = inp
        base = i * vb
        logits = _chunk_logits(x, hc, base, valid_v)
        p = jnp.exp(logits - lse[:, None])          # masked cols -> ~0
        local = labels - base
        in_chunk = (local >= 0) & (local < vb)
        onehot = (jnp.clip(local, 0, vb - 1)[:, None]
                  == jnp.arange(vb)[None, :]) & in_chunk[:, None]
        d_logits = ((p - onehot.astype(p.dtype)) * g[:, None]).astype(x.dtype)
        dx = dx + jnp.einsum("nv,vd->nd", d_logits, hc,
                             preferred_element_type=jnp.float32)
        dhc = jnp.einsum("nv,nd->vd", d_logits, x,
                         preferred_element_type=jnp.float32)
        return dx, dhc.astype(headc.dtype)

    dx, dheadc = lax.scan(body, jnp.zeros(x.shape, jnp.float32),
                          (jnp.arange(k), headc))
    return dx.astype(x.dtype), dheadc, None


_blockwise_ce.defvjp(_blockwise_ce_fwd, _blockwise_ce_bwd)


def fused_cross_entropy(x, head, labels, *, vocab_chunk: int = 4096,
                        reduction: str = "mean", ignore_index: int = -100):
    """Softmax cross-entropy of ``x @ head.T`` against integer ``labels``
    without materialising the logits.

    Labels equal to ``ignore_index`` — or out of ``[0, V)`` entirely —
    contribute zero loss and zero gradient, and ``reduction="mean"``
    divides by the number of VALID tokens (the reference
    ``F.cross_entropy`` ignore_index semantics, loss.py). Without this,
    the common -100 padding convention would gather a masked-lane
    ``-1e30`` gold logit and silently poison the mean with ~1e30.

    Args:
      x: [..., D] hidden states (any float dtype; matmuls accumulate f32).
      head: [V, D] output-projection matrix.
      labels: integer [...] gold class ids.
      vocab_chunk: vocab tile size (static; tail chunk masked).
      reduction: "mean" | "sum" | "none".
      ignore_index: label value to exclude from loss and gradient.
    """
    if not jnp.issubdtype(jnp.asarray(labels).dtype, jnp.integer):
        # the materialising path's take_along_axis would reject float
        # labels too — don't silently floor soft/smoothed targets
        raise TypeError(
            f"fused_cross_entropy: labels must be integer class ids, got "
            f"{jnp.asarray(labels).dtype} (soft labels are not supported)")
    n = 1
    for s in x.shape[:-1]:
        n *= s
    xf = x.reshape(n, x.shape[-1])
    lf = labels.reshape(n).astype(jnp.int32)
    valid = (lf != ignore_index) & (lf >= 0) & (lf < head.shape[0])
    headc, valid_v = _pad_head(head, min(vocab_chunk, head.shape[0]))
    # invalid rows still compute a (finite) loss against class 0; the
    # where() zeroes both their loss and — through its vjp — their g,
    # so the bwd scan's d_logits rows vanish for them
    loss = _blockwise_ce(xf, headc, jnp.where(valid, lf, 0), valid_v)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(
            jnp.sum(valid.astype(loss.dtype)), 1.0)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss.reshape(labels.shape)
