"""Profiler statistics: raw recorder events -> per-name aggregates and
the ``Profiler.summary()`` tables.

Reference capability: python/paddle/profiler/profiler_statistic.py
(HostStatisticNode / EventSummary / _build_table): the layer that turns
the span stream into the user-facing "calls / total / avg / max / min /
ratio" tables. TPU-native simplifications: host spans only (device time
belongs to xprof via jax.profiler — see the package docstring), one
aggregation keyed by span name (the reference's per-TracerEventType
views collapse onto the name prefix the dispatcher already provides),
optional per-thread grouping for ``thread_sep=True``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Iterable, List, Optional

__all__ = ["SortedKeys", "EventStat", "aggregate", "build_table",
           "summary_string"]


class SortedKeys(Enum):
    """Summary-table sort keys (reference: profiler/profiler.py
    SortedKeys). CPU* sort the host-span columns; the GPU* aliases are
    accepted and sort the same columns (device timing lives in xprof
    traces on this runtime, not in the host event stream)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


_SORT_ATTR = {
    SortedKeys.CPUTotal: "total_ns", SortedKeys.GPUTotal: "total_ns",
    SortedKeys.CPUAvg: "avg_ns", SortedKeys.GPUAvg: "avg_ns",
    SortedKeys.CPUMax: "max_ns", SortedKeys.GPUMax: "max_ns",
    SortedKeys.CPUMin: "min_ns", SortedKeys.GPUMin: "min_ns",
}

_UNIT_DIV = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}


@dataclass
class EventStat:
    """Aggregate of every span sharing one name (reference:
    EventSummary.GeneralItem)."""
    name: str
    calls: int = 0
    total_ns: int = 0
    max_ns: int = 0
    min_ns: int = field(default=2 ** 63 - 1)
    ratio: float = 0.0          # total / observed span, percent

    @property
    def avg_ns(self) -> float:
        return self.total_ns / self.calls if self.calls else 0.0

    def add(self, dur_ns: int):
        self.calls += 1
        self.total_ns += dur_ns
        if dur_ns > self.max_ns:
            self.max_ns = dur_ns
        if dur_ns < self.min_ns:
            self.min_ns = dur_ns


def aggregate(events: Iterable[dict],
              span_ns: Optional[int] = None) -> Dict[str, EventStat]:
    """Fold recorder events ({name, begin_ns, end_ns, tid}) into
    per-name stats. ``ratio`` is each name's total against the observed
    window (earliest begin -> latest end, or an explicit ``span_ns``);
    nested spans both bill their full duration, so ratios are
    per-name shares, not a partition of 100% (same property as the
    reference's operator view)."""
    stats: Dict[str, EventStat] = {}
    lo = None
    hi = None
    for e in events:
        b, en = e["begin_ns"], e["end_ns"]
        st = stats.get(e["name"])
        if st is None:
            st = stats[e["name"]] = EventStat(e["name"])
        st.add(en - b)
        if lo is None or b < lo:
            lo = b
        if hi is None or en > hi:
            hi = en
    span = span_ns if span_ns else ((hi - lo) if stats else 0)
    if span:
        for st in stats.values():
            st.ratio = 100.0 * st.total_ns / span
    return stats


def _sort(stats: List[EventStat], sorted_by) -> List[EventStat]:
    attr = _SORT_ATTR.get(sorted_by, "total_ns")
    return sorted(stats, key=lambda s: (-getattr(s, attr), s.name))


def build_table(stats: Dict[str, EventStat], sorted_by=None,
                time_unit: str = "ms", row_limit: int = 0) -> str:
    """Render one aggregation as the reference-shaped text table
    (Name / Calls / Total / Avg / Max / Min / Ratio columns)."""
    if time_unit not in _UNIT_DIV:
        raise ValueError(f"time_unit must be one of {list(_UNIT_DIV)}")
    div = _UNIT_DIV[time_unit]
    rows = _sort(list(stats.values()), sorted_by)
    if row_limit:
        rows = rows[:row_limit]
    u = time_unit
    header = (f"{'Name':<40} {'Calls':>8} {'Total(' + u + ')':>14} "
              f"{'Avg(' + u + ')':>12} {'Max(' + u + ')':>12} "
              f"{'Min(' + u + ')':>12} {'Ratio(%)':>9}")
    sep = "-" * len(header)
    lines = [sep, header, sep]
    for s in rows:
        mn = 0 if s.calls == 0 else s.min_ns
        lines.append(
            f"{s.name[:40]:<40} {s.calls:>8} {s.total_ns / div:>14.3f} "
            f"{s.avg_ns / div:>12.3f} {s.max_ns / div:>12.3f} "
            f"{mn / div:>12.3f} {s.ratio:>9.2f}")
    lines.append(sep)
    return "\n".join(lines)


def summary_string(events: List[dict], sorted_by=None,
                   time_unit: str = "ms", thread_sep: bool = False,
                   span_ns: Optional[int] = None) -> str:
    """The full ``Profiler.summary()`` body: aggregate + render, with
    one table per thread when ``thread_sep``."""
    if not thread_sep:
        return build_table(aggregate(events, span_ns), sorted_by,
                           time_unit)
    by_tid: Dict[int, List[dict]] = {}
    for e in events:
        by_tid.setdefault(e.get("tid", 0), []).append(e)
    parts = []
    for tid in sorted(by_tid):
        parts.append(f"Thread {tid}")
        parts.append(build_table(aggregate(by_tid[tid], span_ns),
                                 sorted_by, time_unit))
    return "\n".join(parts)


def op_breakdown(events: List[dict]) -> dict:
    """Machine-readable per-name stats (calls / total / avg / max / min
    ns + ratio) — the dict the bench and tests consume instead of
    parsing the text table."""
    return {
        name: {"calls": s.calls, "total_ns": s.total_ns,
               "avg_ns": s.avg_ns, "max_ns": s.max_ns,
               "min_ns": 0 if s.calls == 0 else s.min_ns,
               "ratio_pct": round(s.ratio, 4)}
        for name, s in sorted(aggregate(events).items())
    }


__all__.append("op_breakdown")
