"""paddle.profiler parity: scheduler-driven profiling with chrome-trace
export and host-side RecordEvent spans.

Reference capability: python/paddle/profiler/profiler.py:346 (Profiler,
ProfilerState, make_scheduler, export_chrome_tracing) +
paddle/fluid/platform/profiler/host_tracer.cc (host span stream) +
chrometracing_logger.cc (trace export). TPU-native redesign:

- host spans: the native tracer csrc/host_tracer.cc (lock-free per-thread
  buffers, C ABI), JIT-built via utils/cpp_extension.load — the same
  native-runtime layering as the reference; a pure-Python recorder is the
  fallback when no C++ toolchain is present.
- device timing: XLA owns the device; ``Profiler(device_tracing=True)``
  brackets the window with jax.profiler.start_trace/stop_trace (TensorBoard
  format, viewable in xprof/perfetto) instead of the reference's CUPTI
  tracer — the chip-side story the reference gets from cuptiActivity.
- op instrumentation: the dispatcher seam (ops/_op.py) reports each eager
  op through the profile hook when a profiler is recording, the equivalent
  of the reference's generated RecordEvent wrappers in every ad-func.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional
from ..core import enforce as E

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
]


class ProfilerState(Enum):
    """reference: profiler.py ProfilerState."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    """reference: profiler.py ProfilerTarget (CPU/GPU/XPU/CUSTOM_DEVICE);
    the device here is the TPU via XLA."""
    CPU = 0
    TPU = 1
    CUSTOM_DEVICE = 3


# ---------------------------------------------------------------------------
# host span recorders
# ---------------------------------------------------------------------------

class _PyRecorder:
    """Fallback host tracer (pure Python, thread-local span stacks)."""

    def __init__(self):
        self._local = threading.local()
        self._all = []
        self._mu = threading.Lock()
        self.enabled = False
        self._t0 = 0
        self._epoch = 0

    def start(self):
        with self._mu:
            self._all.clear()
        # stale open frames from a span that straddled the previous stop()
        # must not leak into this session (wrong name/duration pairing):
        # frames are epoch-stamped and end() discards old-epoch frames
        self._epoch += 1
        self._t0 = time.perf_counter_ns()
        self.enabled = True

    def stop(self):
        self.enabled = False

    def _stack(self):
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
        return s

    def begin(self, name):
        if self.enabled:
            self._stack().append((name, time.perf_counter_ns(), self._epoch))

    def end(self):
        if not self.enabled:
            return
        st = self._stack()
        while st and st[-1][2] != self._epoch:
            st.pop()   # frame opened in a previous session: discard
        if st:
            name, t0, _ = st.pop()
            with self._mu:
                self._all.append((name, t0, time.perf_counter_ns(),
                                  threading.get_ident() & 0xFFFFFF))

    def events(self):
        with self._mu:
            return [dict(name=n, begin_ns=b, end_ns=e, tid=t)
                    for n, b, e, t in self._all]

    def export(self, path, process_name="paddle_tpu"):
        evs = self.events()
        trace = [{"name": "process_name", "ph": "M", "pid": 0,
                  "args": {"name": process_name}}]
        for e in evs:
            trace.append({"name": e["name"], "ph": "X", "pid": 0,
                          "tid": e["tid"],
                          "ts": (e["begin_ns"] - self._t0) / 1000.0,
                          "dur": (e["end_ns"] - e["begin_ns"]) / 1000.0})
        with open(path, "w") as f:
            json.dump({"traceEvents": trace}, f)
        return 0


class _NativeRecorder:
    """csrc/host_tracer.cc via ctypes (the native runtime path)."""

    def __init__(self, lib):
        self._lib = lib
        import ctypes
        lib.pt_tracer_export.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.pt_record_begin.argtypes = [ctypes.c_char_p]
        lib.pt_record_span.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_uint64]
        lib.pt_event_count.restype = ctypes.c_int64
        lib.pt_now_ns.restype = ctypes.c_uint64
        lib.pt_tracer_dump.restype = ctypes.c_int64

    @property
    def enabled(self):
        return bool(self._lib.pt_tracer_enabled())

    def start(self):
        self._lib.pt_tracer_start()

    def stop(self):
        self._lib.pt_tracer_stop()

    def begin(self, name):
        self._lib.pt_record_begin(name.encode())

    def end(self):
        self._lib.pt_record_end()

    def events(self):
        import ctypes
        n = int(self._lib.pt_event_count())
        if n == 0:
            return []
        names = ctypes.create_string_buffer(120 * n)
        begins = (ctypes.c_uint64 * n)()
        ends = (ctypes.c_uint64 * n)()
        tids = (ctypes.c_uint64 * n)()
        got = int(self._lib.pt_tracer_dump(names, begins, ends, tids, n))
        out = []
        for i in range(got):
            nm = names.raw[i * 120:(i + 1) * 120].split(b"\0", 1)[0]
            out.append(dict(name=nm.decode(), begin_ns=int(begins[i]),
                            end_ns=int(ends[i]), tid=int(tids[i])))
        return out

    def export(self, path, process_name="paddle_tpu"):
        return int(self._lib.pt_tracer_export(path.encode(),
                                              process_name.encode()))


_recorder = None
_recorder_kind = None


def _get_recorder():
    """Build the native tracer on first use; fall back to Python."""
    global _recorder, _recorder_kind
    if _recorder is not None:
        return _recorder
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "csrc", "host_tracer.cc")
    try:
        from ..utils.cpp_extension import load
        lib = load("pt_host_tracer", [src])
        _recorder = _NativeRecorder(lib)
        _recorder_kind = "native"
    except Exception:
        _recorder = _PyRecorder()
        _recorder_kind = "python"
    return _recorder


# ---------------------------------------------------------------------------
# RecordEvent + dispatcher hook
# ---------------------------------------------------------------------------

class RecordEvent:
    """User span (reference: profiler/utils.py RecordEvent) — context
    manager or explicit begin()/end()."""

    def __init__(self, name: str, event_type=None):
        self.name = name

    def begin(self):
        _get_recorder().begin(self.name)

    def end(self):
        _get_recorder().end()

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def _op_span_begin(name):
    r = _recorder
    if r is not None and r.enabled:
        r.begin(name)
        return True
    return False


def _op_span_end():
    r = _recorder
    if r is not None:
        r.end()


# ---------------------------------------------------------------------------
# scheduler + profiler
# ---------------------------------------------------------------------------

def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """reference: profiler.py make_scheduler — step_num -> state."""
    if closed < 0 or ready < 0 or record < 1:
        raise E.InvalidArgumentError("closed/ready must be >=0 and record >=1")
    span = closed + ready + record

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * span:
            return ProfilerState.CLOSED
        pos = s % span
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == span - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def _default_on_trace_ready(prof: "Profiler"):
    pass


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory writing chrome://tracing JSON (reference:
    profiler.py export_chrome_tracing)."""
    os.makedirs(dir_name, exist_ok=True)

    def handle(prof: "Profiler"):
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time() * 1000)}"
                      f".paddle_trace.json")
        _get_recorder().export(path, name)
        prof.last_export_path = path

    return handle


def load_profiler_result(path: str) -> dict:
    """Load a chrome-trace JSON produced by export_chrome_tracing."""
    with open(path) as f:
        return json.load(f)


class Profiler:
    """reference: profiler.py Profiler — scheduler-state-driven windows,
    on_trace_ready callback, optional XLA device tracing."""

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None, timer_only=False,
                 device_tracing: bool = False,
                 device_trace_dir: Optional[str] = None):
        self.targets = list(targets) if targets is not None else [
            ProfilerTarget.CPU]
        if scheduler is None:
            self._scheduler = lambda step: ProfilerState.RECORD
        elif callable(scheduler):
            self._scheduler = scheduler
        else:   # (start, end) tuple: profile [start, end) ONCE (repeat=1)
            lo, hi = scheduler
            self._scheduler = make_scheduler(
                closed=lo, ready=0, record=hi - lo, repeat=1, skip_first=0)
        self.on_trace_ready = on_trace_ready or _default_on_trace_ready
        self.timer_only = timer_only
        self.device_tracing = device_tracing
        self.device_trace_dir = device_trace_dir or "./profiler_device_trace"
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self.last_export_path = None
        self._device_active = False

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._begin_record()
        return self

    def stop(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._end_record()
            self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self):
        prev = self.current_state
        rec = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev == ProfilerState.RECORD_AND_RETURN:
            self._end_record()
            self.on_trace_ready(self)
        recording = prev in rec and prev != ProfilerState.RECORD_AND_RETURN
        self.step_num += 1
        nxt = self._scheduler(self.step_num)
        if nxt in rec and not recording:
            self._begin_record()
        elif recording and nxt not in rec:
            self._end_record()
        self.current_state = nxt

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- internals ---------------------------------------------------------
    def _begin_record(self):
        if self.timer_only:
            return
        rec = _get_recorder()
        if not rec.enabled:
            rec.start()
        from ..ops import _op
        _op.set_profile_hook(_op_span_begin, _op_span_end)
        if self.device_tracing and not self._device_active:
            try:
                import jax
                jax.profiler.start_trace(self.device_trace_dir)
                self._device_active = True
            except Exception:
                self._device_active = False

    def _end_record(self):
        if self.timer_only:
            return
        from ..ops import _op
        _op.set_profile_hook(None, None)
        _get_recorder().stop()
        if self._device_active:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_active = False

    # -- reporting ---------------------------------------------------------
    def events(self):
        return _get_recorder().events()

    def export(self, path: str, format: str = "json"):
        if format not in ("json", "chrome"):
            raise E.InvalidArgumentError("only chrome-trace json export is supported")
        _get_recorder().export(path)
        self.last_export_path = path
        return path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregate span stats per name and render the reference-shaped
        table — calls/total/avg/max/min/ratio columns, sortable by
        ``SortedKeys`` (reference: profiler.py summary ->
        profiler_statistic._build_table). Prints and returns the table
        string; ``statistics.op_breakdown(self.events())`` gives the
        machine-readable form."""
        from .statistics import summary_string
        table = summary_string(self.events(), sorted_by=sorted_by,
                               time_unit=time_unit, thread_sep=thread_sep)
        print(table)
        return table


from .statistics import SortedKeys  # noqa: E402  (single definition home)


class SummaryView(Enum):
    """Summary view selection (reference: profiler/profiler.py
    SummaryView)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory writing the raw trace dict as a pickled
    protobuf-stand-in artifact (reference: profiler.py export_protobuf;
    the chrome-trace JSON remains the interchange format on this
    runtime)."""
    import os
    import pickle
    import socket
    import time as _time

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{socket.gethostname()}"
        path = os.path.join(
            dir_name,
            f"{name}_time_{int(_time.time() * 1000)}.paddle_trace.pb")
        json_path = path + ".json"
        _get_recorder().export(json_path, name)
        with open(json_path) as f:
            trace = json.load(f)
        os.remove(json_path)
        with open(path, "wb") as f:
            pickle.dump(trace, f)
        prof.last_export_path = path

    return handler


__all__ += ["SortedKeys", "SummaryView", "export_protobuf"]


class TracerEventType(Enum):
    """Host-span categories (reference:
    profiler/profiler_statistic.py TracerEventType; values mirror the
    reference enum so exported traces classify identically)."""
    Operator = 0
    Dataloader = 1
    ProfileStep = 2
    CudaRuntime = 3
    Kernel = 4
    Memcpy = 5
    Memset = 6
    UserDefined = 7
    OperatorInner = 8
    Forward = 9
    Backward = 10
    Optimization = 11
    Communication = 12
    PythonOp = 13
    PythonUserDefined = 14


__all__.append("TracerEventType")
