"""paddle.version parity (reference: generated at build by setup.py —
python/paddle/__init__.py:16 imports full_version/commit/cuda()/etc.).
This build is CUDA-free by design; device queries answer for the TPU."""
from __future__ import annotations

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = False
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"
cinn_version = "False"
tensorrt_version = None


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("tpu: True")
    print("cuda: False")
    print("cudnn: False")


def cuda():
    return "False"


def cudnn():
    return "False"


def nccl():
    return "False"


def xpu():
    return "False"


def xpu_xccl():
    return "False"


def cinn():
    return "False"


def tpu():
    return "True"
