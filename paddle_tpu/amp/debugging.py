"""paddle.amp.debugging parity: operator stats collection + tensor
checking.

Reference capability: python/paddle/amp/debugging.py (DebugMode,
TensorCheckerConfig, enable/disable_operator_stats_collection,
collect_operator_stats, check_numerics, compare_accuracy,
enable/disable_tensor_checker).

TPU-native: op-level dtype stats ride the dispatcher's profile hook
(ops/_op.py _PROFILE_HOOK) — every dispatched op is counted by name;
numerics checking rides the same nan/inf machinery as
FLAGS_check_nan_inf.
"""
from __future__ import annotations

import contextlib
import enum
from collections import Counter

import jax.numpy as jnp

from ..ops import _op as _op_mod
from ..core import enforce as E

__all__ = ["DebugMode", "TensorCheckerConfig", "check_numerics",
           "check_layer_numerics", "collect_operator_stats",
           "compare_accuracy", "disable_operator_stats_collection",
           "disable_tensor_checker", "enable_operator_stats_collection",
           "enable_tensor_checker"]


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    CHECK_ALL_ABORT = 4
    CHECK_ALL_ABORT_AND_DUMP = 5
    DUMP_ALL = 6


_op_counts: Counter = Counter()
_collecting = False
_saved_hook = None


def _count_begin(name):
    _op_counts[name] += 1


def _count_end():
    pass


def enable_operator_stats_collection():
    """Count every dispatched op by name until disabled (reference
    prints a dtype-bucketed table; the dispatcher is dtype-agnostic at
    this seam so the table is per-op call counts)."""
    global _collecting, _saved_hook
    if _collecting:
        return
    _saved_hook = _op_mod._PROFILE_HOOK
    _op_mod.set_profile_hook(_count_begin, _count_end)
    _collecting = True


def disable_operator_stats_collection():
    global _collecting, _saved_hook
    if not _collecting:
        return
    if _saved_hook is not None:
        _op_mod.set_profile_hook(_saved_hook[0], _saved_hook[1])
    else:
        _op_mod.set_profile_hook(None, None)
    _collecting = False
    if _op_counts:
        width = max(len(k) for k in _op_counts)
        print("<------------------------------ op list "
              "------------------------------->")
        for name, cnt in _op_counts.most_common():
            print(f"  {name:<{width}}  calls: {cnt}")
        print("<----------------------------------- end "
              "----------------------------->")
    _op_counts.clear()


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


class TensorCheckerConfig:
    """reference: debugging.py TensorCheckerConfig."""

    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    from ..core.flags import set_flags

    if checker_config.enable:
        set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    from ..core.flags import set_flags

    set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Raise on nan/inf in ``tensor`` (reference: debugging.py
    check_numerics)."""
    from ..ops._op import unwrap

    arr = unwrap(tensor)
    if jnp.issubdtype(arr.dtype, jnp.floating):
        bad = ~jnp.isfinite(arr)
        n_nan = int(jnp.sum(jnp.isnan(arr)))
        n_inf = int(jnp.sum(jnp.isinf(arr)))
        if bool(jnp.any(bad)):
            raise E.PreconditionNotMetError(
                f"check_numerics: {op_type or 'tensor'} {var_name} has "
                f"{n_nan} nan / {n_inf} inf values")
    return tensor


def check_layer_numerics(func):
    """Decorator checking a Layer forward's inputs/outputs for nan/inf
    (reference: debugging.py check_layer_numerics)."""
    import functools

    from ..core.tensor import Tensor

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        for a in args:
            if isinstance(a, Tensor):
                check_numerics(a, type(self).__name__, "input")
        out = func(self, *args, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if isinstance(o, Tensor):
                check_numerics(o, type(self).__name__, "output")
        return out

    return wrapper


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    raise NotImplementedError(
        "compare_accuracy consumes the reference's nan-inf dump files, a "
        "GPU-kernel-level artifact this runtime does not produce; compare "
        "checkpoints/outputs directly instead")
