"""Automatic mixed precision (reference: python/paddle/amp/auto_cast.py).

TPU-native notes: bfloat16 is the native half type (no loss scaling needed);
float16 is supported for parity and pairs with GradScaler. O1 casts per-op by
white/black list at the dispatcher seam (ops/_op.py consults
``current_cast_dtype_for``); O2 casts whole layers via ``decorate`` keeping
norm params in float32 + float32 master weights in the optimizer.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax.numpy as jnp

from ..core.dtype import convert_dtype
from . import amp_lists

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate",
           "is_auto_cast_enabled", "current_cast_dtype_for", "white_list",
           "black_list"]


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.white = frozenset()
        self.black = frozenset()


_amp = _AmpState()


def is_auto_cast_enabled() -> bool:
    return _amp.enabled


def white_list():
    return _amp.white


def black_list():
    return _amp.black


def current_cast_dtype_for(opname: str):
    """Called by the op dispatcher per call. Returns the dtype float inputs
    should be cast to, or None to leave them untouched."""
    if not _amp.enabled:
        return None
    if opname in _amp.white:
        return _amp.dtype
    if opname in _amp.black:
        return jnp.float32
    return None


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None,
              custom_black_list=None, level: str = "O1",
              dtype: str = "bfloat16", use_promote: bool = True):
    """paddle.amp.auto_cast parity (auto_cast.py amp_guard)."""
    dt = convert_dtype(dtype)  # validate before touching global state
    white = set(amp_lists.WHITE_LIST)
    black = set(amp_lists.BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    if level == "O2":
        # O2: everything not blacklisted runs in the low dtype; the layer
        # params were already cast by decorate(); treat white as "all".
        black -= white
    prev = (_amp.enabled, _amp.dtype, _amp.level, _amp.white, _amp.black)
    try:
        _amp.enabled = bool(enable)
        _amp.dtype = dt
        _amp.level = level
        _amp.white = frozenset(white)
        _amp.black = frozenset(black)
        yield
    finally:
        (_amp.enabled, _amp.dtype, _amp.level, _amp.white,
         _amp.black) = prev


amp_guard = auto_cast

_KEEP_FP32_LAYERS = ("BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
                     "RMSNorm", "SyncBatchNorm")


def decorate(models, optimizers=None, level: str = "O2",
             dtype: str = "bfloat16", master_weight=None,
             save_dtype=None, master_grad=False, excluded_layers=None):
    """paddle.amp.decorate parity: O2 casts model params to the low dtype,
    keeping norm layers in float32 (reference: auto_cast.py amp_decorate)."""
    dt = convert_dtype(dtype)
    model_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        for model in model_list:
            for layer in model.sublayers(include_self=True):
                name = type(layer).__name__
                if any(name.startswith(k) for k in _KEEP_FP32_LAYERS):
                    continue
                if excluded_layers and isinstance(
                        layer, tuple(excluded_layers)):
                    continue
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(
                            p._data.dtype, jnp.floating):
                        p._data = p._data.astype(dt)
        if optimizers is not None:
            opt_list = optimizers if isinstance(
                optimizers, (list, tuple)) else [optimizers]
            for o in opt_list:
                if hasattr(o, "_multi_precision"):
                    o._multi_precision = True
    if optimizers is None:
        return models if isinstance(models, (list, tuple)) else model_list[0]
    return (models if isinstance(models, (list, tuple)) else model_list[0],
            optimizers)


amp_decorate = decorate
