"""paddle.amp parity surface (reference: python/paddle/amp/__init__.py)."""
from . import amp_lists  # noqa
from .auto_cast import (amp_decorate, amp_guard, auto_cast, black_list,  # noqa
                        current_cast_dtype_for, decorate,
                        is_auto_cast_enabled, white_list)
from .grad_scaler import AmpScaler, GradScaler, OptimizerState  # noqa


def is_float16_supported(device=None):
    """fp16 support probe (reference: amp/auto_cast.py). TPU computes
    fp16 via upcast; MXU-native half dtype is bfloat16."""
    import jax

    return jax.default_backend() in ("tpu", "gpu", "axon")


def is_bfloat16_supported(device=None):
    """bf16 is the native TPU half dtype; CPU XLA also executes it."""
    return True
