"""paddle.amp parity surface (reference: python/paddle/amp/__init__.py)."""
from . import amp_lists  # noqa
from .auto_cast import (amp_decorate, amp_guard, auto_cast, black_list,  # noqa
                        current_cast_dtype_for, decorate,
                        is_auto_cast_enabled, white_list)
from .grad_scaler import AmpScaler, GradScaler, OptimizerState  # noqa
