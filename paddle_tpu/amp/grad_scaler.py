"""Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py
AmpScaler:41 / GradScaler:619).

Needed for float16 only — bfloat16 has fp32's exponent range, so the scaler
becomes a transparent no-op when grads stay finite (use_dynamic_loss_scaling
still honored for parity).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import enforce as E

__all__ = ["GradScaler", "AmpScaler", "OptimizerState"]


class OptimizerState:
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class GradScaler:
    def __init__(self, enable: bool = True,
                 init_loss_scaling: float = 65536.0,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 2000,
                 decr_every_n_nan_or_inf: int = 1,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False          # any-optimizer aggregate (for update)
        self._opt_states = {}            # id(opt) -> (state, found_inf)

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, v: float):
        self._scale = float(v)

    def scale(self, loss: Tensor) -> Tensor:
        if not self._enable:
            return loss
        from .. import ops
        return ops.scale(loss, scale=self._scale)

    def _grads_of(self, optimizer):
        return [p for p in (optimizer._parameter_list or [])
                if p.grad is not None and not p.stop_gradient]

    def unscale_(self, optimizer):
        if not self._enable:
            return
        st, _ = self._opt_states.get(id(optimizer),
                                     (OptimizerState.INIT, False))
        if st == OptimizerState.UNSCALED:
            return
        if st == OptimizerState.STEPPED:
            raise E.PreconditionNotMetError(
                "unscale_() is being called after step() for this optimizer; "
                "call update() first (reference: grad_scaler.py)")
        inv = 1.0 / self._scale
        # One fused finiteness check: accumulate per-grad flags on device,
        # materialize a single scalar at the end (no per-param host sync).
        found_acc = jnp.zeros((), jnp.bool_)
        for p in self._grads_of(optimizer):
            g = p.grad._data * inv
            found_acc = found_acc | jnp.any(~jnp.isfinite(g))
            p.grad._data = g
        found = bool(found_acc)
        # Per-optimizer flag: another optimizer's clean grads must not clear
        # this one's inf result (and vice versa).
        self._found_inf = self._found_inf or found
        self._opt_states[id(optimizer)] = (OptimizerState.UNSCALED, found)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        st, _ = self._opt_states.get(id(optimizer),
                                     (OptimizerState.INIT, False))
        if st == OptimizerState.STEPPED:
            raise E.PreconditionNotMetError(
                "step() has already been called for this optimizer since the "
                "last update()")
        if st != OptimizerState.UNSCALED:
            self.unscale_(optimizer)
        _, found = self._opt_states[id(optimizer)]
        if not found:
            optimizer.step()
        self._opt_states[id(optimizer)] = (OptimizerState.STEPPED, found)

    def update(self):
        if not self._enable or not self._dynamic:
            self._opt_states.clear()
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._opt_states.clear()

    def minimize(self, optimizer, loss):
        # loss is assumed already scaled (reference AmpScaler.minimize)
        self.step(optimizer)
        self.update()

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
        self._dynamic = state.get("use_dynamic_loss_scaling", self._dynamic)


AmpScaler = GradScaler
