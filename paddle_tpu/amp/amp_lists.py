"""AMP op allow/deny lists (reference: python/paddle/amp/amp_lists.py
FP16_WHITE_LIST / FP16_BLACK_LIST).

White: MXU-bound ops that are fast and safe in half precision.
Black: numerically sensitive ops forced to float32.
Everything else runs in whatever dtype its inputs arrive in.
"""

WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "einsum", "bmm", "mm",
    "_sdpa_op", "_flash_attention_op", "bilinear",
}

BLACK_LIST = {
    "exp", "expm1", "log", "log2", "log10", "log1p", "pow", "square",
    "sqrt", "rsqrt", "softmax", "log_softmax", "cross_entropy",
    "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "kl_div", "mse_loss",
    "l1_loss", "layer_norm", "rms_norm", "_batch_norm_train",
    "_batch_norm_eval", "instance_norm", "group_norm", "local_response_norm",
    "mean", "sum", "cumsum", "cumprod", "logsumexp", "norm", "var", "std",
    "sigmoid_focal_loss", "erf", "erfinv", "cosine_similarity",
}
