"""paddle.sparse.nn.functional parity.

Reference capability: python/paddle/sparse/nn/functional/ (conv.py
conv2d/conv3d/subm_conv*, pooling.py max_pool3d, activation.py,
transformer.py attention). TPU-native realization: sparse activations
run in value space over the nonzeros (pattern preserved); sparse
convolution evaluates as dense conv on the materialized tensor with the
result re-sparsified — on TPU the dense conv IS the fast path at the
occupancies these APIs see (XLA/MXU), and submanifold variants mask the
output back to the input's active sites (the defining subm property).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from ...core import enforce as E

__all__ = ["conv2d", "conv3d", "subm_conv2d", "subm_conv3d",
           "subm_conv2d_igemm", "subm_conv3d_igemm", "max_pool3d",
           "relu", "relu6", "leaky_relu", "softmax", "attention"]


def _parent():
    from ... import sparse as S

    return S


def relu(x, name=None):
    return _parent().relu(x)


def relu6(x, name=None):
    S = _parent()
    return S._unary(lambda v: jnp.clip(v, 0, 6.0))(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    S = _parent()
    return S._unary(
        lambda v: jnp.where(v >= 0, v, negative_slope * v))(x)


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over the sparsity pattern (reference:
    sparse/nn/functional/activation.py softmax): zeros stay zero, the
    stored entries of each row renormalize among themselves. Only the
    last axis is supported, like the reference."""
    if axis not in (-1, len(x.shape) - 1):
        raise E.InvalidArgumentError(
            f"sparse softmax only supports the last axis, got {axis}")
    S = _parent()
    from jax.experimental import sparse as jsparse

    sp = x._sp
    dense = sp.todense()
    neg_inf = jnp.where(dense == 0, -jnp.inf, dense)
    sm = jax.nn.softmax(neg_inf, axis=-1)
    sm = jnp.where(dense == 0, 0.0, sm)
    if isinstance(sp, jsparse.BCSR):
        return S.SparseCsrTensor(jsparse.BCSR.fromdense(sm))
    return S.SparseCooTensor(jsparse.BCOO.fromdense(sm))


def _dense_conv(x, weight, bias, stride, padding, dilation, groups, nsp,
                subm, data_format):
    """Dense-detour sparse conv: densify -> lax conv -> re-sparsify.
    x: SparseCooTensor with dense shape [N, *spatial, C] (reference
    NDHWC/NHWC layouts); weight [*k, C/groups, M]."""
    S = _parent()
    import numpy as np

    dense = x._sp.todense()
    w = weight._data if hasattr(weight, "_data") else jnp.asarray(weight)
    k_sp = w.shape[:nsp]
    # NHWC/NDHWC -> NC* for lax, conv, then back
    perm_in = (0, nsp + 1) + tuple(range(1, nsp + 1))
    xc = jnp.transpose(dense, perm_in)
    # weight [*k, Cin/g, M] -> [M, Cin/g, *k]
    wc = jnp.transpose(w, (nsp + 1, nsp) + tuple(range(nsp)))
    if isinstance(stride, int):
        stride = (stride,) * nsp
    if isinstance(dilation, int):
        dilation = (dilation,) * nsp
    if subm:
        # submanifold: same spatial size, output active only at input's
        # active sites
        pads = [((k - 1) // 2 * d, (k - 1) // 2 * d)
                for k, d in zip(k_sp, dilation)]
        stride = (1,) * nsp
    elif isinstance(padding, int):
        pads = [(padding * 1, padding * 1)] * nsp
    else:
        pads = [(p, p) if isinstance(p, int) else tuple(p)
                for p in padding]
    out = jax.lax.conv_general_dilated(
        xc, wc, window_strides=stride, padding=pads,
        rhs_dilation=dilation, feature_group_count=groups)
    if bias is not None:
        b = bias._data if hasattr(bias, "_data") else jnp.asarray(bias)
        out = out + b.reshape((1, -1) + (1,) * nsp)
    perm_out = (0,) + tuple(range(2, nsp + 2)) + (1,)
    out = jnp.transpose(out, perm_out)
    if subm:
        # mask to the input's active sites (any-channel occupancy)
        occupied = jnp.any(dense != 0, axis=-1, keepdims=True)
        out = jnp.where(occupied, out, 0.0)
    from jax.experimental import sparse as jsparse

    return S.SparseCooTensor(jsparse.BCOO.fromdense(out))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       3, False, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       2, False, data_format)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       3, True, data_format)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       2, True, data_format)


# igemm variants: the reference's implicit-GEMM kernel selection — same
# math, different GPU kernel; here they are the same lowering
subm_conv2d_igemm = subm_conv2d
subm_conv3d_igemm = subm_conv3d


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse 3D max pool (reference: sparse/nn/functional/pooling.py):
    dense-detour reduce_window over NDHWC."""
    S = _parent()
    from jax import lax
    from jax.experimental import sparse as jsparse

    sp = x._sp
    dense = sp.todense()
    # max over STORED values only (reference sparse pooling): inactive
    # sites must not inject zeros into the max — mask them to -inf via
    # the occupancy pattern, then zero windows with no active site
    n_idx = sp.indices.shape[1]
    ones = jnp.ones((sp.indices.shape[0],), dense.dtype)
    occ = jsparse.BCOO((ones, sp.indices),
                       shape=sp.shape[:n_idx]).todense()
    occ = occ.reshape(occ.shape + (1,) * (dense.ndim - occ.ndim))
    masked = jnp.where(occ > 0, dense, -jnp.inf)
    k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    s = k if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dims = (1,) + k + (1,)
    strides = (1,) + s + (1,)
    pads = [(0, 0)] + [(pp, pp) for pp in p] + [(0, 0)]
    out = lax.reduce_window(masked, -jnp.inf, lax.max, dims, strides, pads)
    out = jnp.where(jnp.isfinite(out), out, 0.0)
    return S.SparseCooTensor(jsparse.BCOO.fromdense(out))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (reference:
    sparse/nn/functional/transformer.py attention): the CSR sparse_mask
    selects which logits exist. Delegates to the dense masked softmax
    (the TPU fast path) honoring the mask's pattern."""
    from ...nn.functional.extras import sparse_attention as _sa

    crows = sparse_mask.crows()
    cols = sparse_mask.cols()
    import numpy as np

    b, h, s, _ = query.shape
    off = np.tile(np.asarray(crows.numpy())[None, None], (b, h, 1))
    cc = np.tile(np.asarray(cols.numpy())[None, None], (b, h, 1))
    from ...core.tensor import Tensor

    return _sa(query, key, value, Tensor(jnp.asarray(off)),
               Tensor(jnp.asarray(cc)), key_padding_mask, attn_mask)
