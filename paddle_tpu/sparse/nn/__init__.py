"""paddle.sparse.nn parity: sparse Layer classes over the functional
surface (reference: python/paddle/sparse/nn/layer/)."""
from __future__ import annotations

from ...nn.initializer import Constant, XavierUniform
from ...nn.layer.base import Layer
from . import functional  # noqa: F401
from . import functional as F

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv2D", "Conv3D",
           "SubmConv2D", "SubmConv3D", "MaxPool3D", "BatchNorm",
           "SyncBatchNorm"]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class _SparseConv(Layer):
    _nsp = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None,
                 data_format=None, key=None):
        super().__init__()
        nsp = self._nsp
        k = (kernel_size,) * nsp if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format or ("NDHWC" if nsp == 3 else "NHWC")
        # reference layout: [*k, Cin/groups, Cout]
        self.weight = self.create_parameter(
            k + (in_channels // groups, out_channels), attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, x):
        fn = {(2, False): F.conv2d, (3, False): F.conv3d,
              (2, True): F.subm_conv2d, (3, True): F.subm_conv3d}[
            (self._nsp, self._subm)]
        return fn(x, self.weight, self.bias, self.stride, self.padding,
                  self.dilation, self.groups, self.data_format)


class Conv2D(_SparseConv):
    _nsp = 2


class Conv3D(_SparseConv):
    _nsp = 3


class SubmConv2D(_SparseConv):
    _nsp = 2
    _subm = True


class SubmConv3D(_SparseConv):
    _nsp = 3
    _subm = True


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class BatchNorm(Layer):
    """Sparse batch norm over channel-last nonzero values (reference:
    sparse/nn/layer/norm.py BatchNorm): statistics over the stored
    values per channel."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        import jax.numpy as jnp

        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.register_buffer("_mean", jnp.zeros((num_features,)))
        self.register_buffer("_variance", jnp.ones((num_features,)))

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        from jax.experimental import sparse as jsparse

        from .. import SparseCooTensor

        sp = x._sp
        vals = sp.data
        c = self.weight._data.shape[0]
        if vals.ndim == 2:
            # n_dense=1 layout: values [nnz, C]
            if self.training:
                mu = jnp.mean(vals, axis=0)
                var = jnp.var(vals, axis=0)
            else:
                mu, var = self._mean._data, self._variance._data
            new = ((vals - mu) / jnp.sqrt(var + self.epsilon)
                   * self.weight._data + self.bias._data)
        else:
            # fully-sparse layout: values [nnz], channel = last coordinate
            ch = sp.indices[:, -1]
            if self.training:
                cnt = jnp.maximum(
                    jax.ops.segment_sum(jnp.ones_like(vals), ch, c), 1.0)
                mu = jax.ops.segment_sum(vals, ch, c) / cnt
                var = jax.ops.segment_sum(
                    (vals - mu[ch]) ** 2, ch, c) / cnt
            else:
                mu, var = self._mean._data, self._variance._data
            new = ((vals - mu[ch]) / jnp.sqrt(var[ch] + self.epsilon)
                   * self.weight._data[ch] + self.bias._data[ch])
        if self.training:
            self._mean._data = (self.momentum * self._mean._data
                                + (1 - self.momentum) * mu)
            self._variance._data = (self.momentum * self._variance._data
                                    + (1 - self.momentum) * var)
        return SparseCooTensor(jsparse.BCOO((new, sp.indices),
                                            shape=sp.shape))


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse batch norm. Under the single-controller
    mesh model, batch statistics computed inside a jitted sharded
    program are already global (XLA inserts the reductions) — matching
    the reference's converted SyncBatchNorm semantics."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.weight.shape[0],
                                momentum=layer.momentum,
                                epsilon=layer.epsilon)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean._data = layer._mean._data
            new._variance._data = layer._variance._data
            return new
        for name, sub in getattr(layer, "_sub_layers", {}).items():
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer
