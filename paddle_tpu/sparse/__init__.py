"""paddle.sparse parity: COO/CSR sparse tensors + ops + nn.

Reference capability: python/paddle/sparse/ (5.2K LoC — creation, unary/
binary math, matmul, masked ops, sparse nn layers over phi sparse
kernels, paddle/phi/core/sparse_coo_tensor.h). TPU-native redesign:
storage is jax.experimental.sparse BCOO/BCSR — XLA lowers sparse ops to
dense-friendly gather/scatter/segment kernels, which is how sparsity is
actually profitable on the MXU (no cuSPARSE analogue needed). The Tensor
facade keeps paddle's API: SparseCooTensor/SparseCsrTensor behave like
Tensors with .indices()/.values()/.to_dense().
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops._op import unwrap, wrap

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_same_shape", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "relu", "abs", "sin", "tanh",
    "sqrt", "square", "pow", "neg", "cast", "transpose", "sum",
    "coalesce", "nn", "asin", "asinh", "atan", "atanh", "sinh", "tan",
    "deg2rad", "rad2deg", "isnan", "reshape", "slice", "mv", "addmm",
    "pca_lowrank", "expm1", "log1p",
]


class SparseCooTensor(Tensor):
    """COO sparse tensor (reference: phi/core/sparse_coo_tensor.h) backed
    by a BCOO array in ``_sp``; ``_data`` holds the dense view lazily."""

    def __init__(self, bcoo):
        self._sp = bcoo
        super().__init__(None)
        self._data = None

    # -- paddle surface ----------------------------------------------------
    def indices(self) -> Tensor:
        return wrap(self._sp.indices.T)     # paddle: [ndim, nnz]

    def values(self) -> Tensor:
        return wrap(self._sp.data)

    def nnz(self) -> int:
        return int(self._sp.nse)

    def to_dense(self) -> Tensor:
        return wrap(self._sp.todense())

    def to_sparse_csr(self):
        dense = self._sp.todense()
        return sparse_csr_tensor_from_dense(dense)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self):
        return SparseCooTensor(self._sp.sum_duplicates())

    @property
    def shape(self):
        return list(self._sp.shape)

    @property
    def dtype(self):
        return self._sp.dtype

    @property
    def ndim(self):
        return self._sp.ndim

    def numpy(self):
        return np.asarray(self._sp.todense())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor(Tensor):
    """CSR sparse tensor (reference: phi/core/sparse_csr_tensor.h) backed
    by BCSR."""

    def __init__(self, bcsr):
        self._sp = bcsr
        super().__init__(None)
        self._data = None

    def crows(self) -> Tensor:
        return wrap(self._sp.indptr)

    def cols(self) -> Tensor:
        return wrap(self._sp.indices)

    def values(self) -> Tensor:
        return wrap(self._sp.data)

    def nnz(self) -> int:
        return int(self._sp.nse)

    def to_dense(self) -> Tensor:
        return wrap(self._sp.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return sparse_coo_tensor_from_dense(self._sp.todense())

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    @property
    def shape(self):
        return list(self._sp.shape)

    @property
    def dtype(self):
        return self._sp.dtype

    @property
    def ndim(self):
        return self._sp.ndim

    def numpy(self):
        return np.asarray(self._sp.todense())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# ---------------------------------------------------------------------------
# creation (reference: sparse/creation.py)
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(unwrap(indices))           # [ndim, nnz] (paddle)
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in jnp.max(idx, axis=1))
    bcoo = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_coo_tensor_from_dense(dense):
    return SparseCooTensor(jsparse.BCOO.fromdense(jnp.asarray(dense)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    crows = jnp.asarray(unwrap(crows))
    cols = jnp.asarray(unwrap(cols))
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    bcsr = jsparse.BCSR((vals, cols, crows), shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def sparse_csr_tensor_from_dense(dense):
    return SparseCsrTensor(jsparse.BCSR.fromdense(jnp.asarray(dense)))


def _to_sp(x):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return x._sp
    return jnp.asarray(unwrap(x))


def _rewrap(sp, like):
    if isinstance(like, SparseCsrTensor):
        if isinstance(sp, jsparse.BCSR):
            return SparseCsrTensor(sp)
        return SparseCsrTensor(jsparse.BCSR.fromdense(sp.todense()
                               if hasattr(sp, "todense") else sp))
    if isinstance(sp, jsparse.BCOO):
        return SparseCooTensor(sp)
    if isinstance(sp, jsparse.BCSR):
        return SparseCooTensor(jsparse.BCOO.fromdense(sp.todense()))
    return SparseCooTensor(jsparse.BCOO.fromdense(jnp.asarray(sp)))


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# elementwise (reference: sparse/unary.py, binary.py) — value-space ops
# keep the sparsity pattern; zero-preserving by construction
# ---------------------------------------------------------------------------

def _unary(fn):
    def op(x, name=None):
        sp = x._sp
        if isinstance(sp, jsparse.BCSR):
            new = jsparse.BCSR((fn(sp.data), sp.indices, sp.indptr),
                               shape=sp.shape)
        else:
            new = jsparse.BCOO((fn(sp.data), sp.indices), shape=sp.shape)
        return _rewrap(new, x)
    return op


relu = _unary(lambda v: jnp.maximum(v, 0))
abs = _unary(jnp.abs)
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
neg = _unary(jnp.negative)
expm1 = _unary(jnp.expm1)
log1p = _unary(jnp.log1p)


def pow(x, factor, name=None):
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core.dtype import convert_dtype
    vd = convert_dtype(value_dtype) if value_dtype is not None else None
    return _unary(lambda v: v.astype(vd) if vd is not None else v)(x)


def _dense(x):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return x._sp.todense()
    return jnp.asarray(unwrap(x))


def _is_sp(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def _same_pattern(a, b) -> bool:
    if isinstance(a, jsparse.BCOO) and isinstance(b, jsparse.BCOO):
        return (a.indices.shape == b.indices.shape
                and bool(jnp.all(a.indices == b.indices)))
    if isinstance(a, jsparse.BCSR) and isinstance(b, jsparse.BCSR):
        return (a.indices.shape == b.indices.shape
                and bool(jnp.all(a.indices == b.indices))
                and bool(jnp.all(a.indptr == b.indptr)))
    return False


def _value_space(sp, data):
    if isinstance(sp, jsparse.BCSR):
        return jsparse.BCSR((data, sp.indices, sp.indptr), shape=sp.shape)
    return jsparse.BCOO((data, sp.indices), shape=sp.shape)


def _binary(fn, concat_ok=False, scalar_value_space=False):
    """Binary op staying sparse where possible: same-pattern operands (and,
    for mul/div only, scalars — add/sub with a scalar changes implicit
    zeros and must densify) run in value space; sparse+sparse add/sub
    unions indices via concat + sum_duplicates; everything else (dense
    operand, sparse*sparse intersection) falls back to dense — the
    reference's sparse kernels have the same structural cases
    (phi/kernels/sparse/elementwise_*)."""

    def op(x, y, name=None):
        if scalar_value_space and _is_sp(x) and not _is_sp(y) \
                and jnp.ndim(unwrap(y)) == 0:
            return _rewrap(_value_space(x._sp, fn(x._sp.data, unwrap(y))), x)
        if _is_sp(x) and _is_sp(y):
            a, b = x._sp, y._sp
            if _same_pattern(a, b):
                return _rewrap(_value_space(a, fn(a.data, b.data)), x)
            if concat_ok:
                aco = a if isinstance(a, jsparse.BCOO) else \
                    jsparse.BCOO.fromdense(a.todense())
                bco = b if isinstance(b, jsparse.BCOO) else \
                    jsparse.BCOO.fromdense(b.todense())
                bdata = fn(jnp.zeros_like(bco.data), bco.data)
                merged = jsparse.BCOO(
                    (jnp.concatenate([aco.data, bdata]),
                     jnp.concatenate([aco.indices, bco.indices])),
                    shape=aco.shape).sum_duplicates()
                return _rewrap(merged, x)
        dense = fn(_dense(x), _dense(y))
        return _rewrap(jsparse.BCOO.fromdense(dense), x if _is_sp(x) else y)

    return op


add = _binary(jnp.add, concat_ok=True)
subtract = _binary(jnp.subtract, concat_ok=True)
multiply = _binary(jnp.multiply, scalar_value_space=True)
divide = _binary(jnp.divide, scalar_value_space=True)


# ---------------------------------------------------------------------------
# matmul / reductions (reference: sparse/matmul.py)
# ---------------------------------------------------------------------------

def matmul(x, y, name=None):
    """sparse @ dense -> dense (the TPU-profitable direction; XLA lowers
    BCOO matmul to gather+segment-sum)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        out = x._sp @ _dense(y)
        return wrap(out.todense() if hasattr(out, "todense") else out)
    out = jnp.asarray(unwrap(x)) @ _dense(y)
    return wrap(out)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated only at mask's nonzeros (reference:
    sparse/matmul.py masked_matmul — the SDDMM kernel)."""
    xa, ya = jnp.asarray(unwrap(x)), jnp.asarray(unwrap(y))
    msp = mask._sp if isinstance(mask, (SparseCooTensor, SparseCsrTensor)) \
        else jsparse.BCOO.fromdense(jnp.asarray(unwrap(mask)))
    if isinstance(msp, jsparse.BCSR):
        msp = jsparse.BCOO.fromdense(msp.todense())
    rows = msp.indices[:, 0]
    cols = msp.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xa[rows, :], ya[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, msp.indices),
                                        shape=(xa.shape[0], ya.shape[1])))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = _dense(x).sum(axis=axis, keepdims=keepdim)
    return wrap(d)


def transpose(x, perm, name=None):
    dense = jnp.transpose(_dense(x), perm)
    return _rewrap(jsparse.BCOO.fromdense(dense), x)


def coalesce(x, name=None):
    return x.coalesce()


# ---------------------------------------------------------------------------
# sparse nn (reference: sparse/nn — ReLU layer + Linear-ish)
# ---------------------------------------------------------------------------

# sparse.nn is a real subpackage (sparse/nn/) with Layer classes +
# functional; import explicitly (attribute would shadow the submodule)
import importlib as _importlib

nn = _importlib.import_module(".nn", __name__)


# -- unary long tail (reference: sparse/unary.py full op list) --------------

asin = _unary(jnp.arcsin)
asinh = _unary(jnp.arcsinh)
atan = _unary(jnp.arctan)
atanh = _unary(jnp.arctanh)
sinh = _unary(jnp.sinh)
tan = _unary(jnp.tan)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
isnan = _unary(jnp.isnan)


def reshape(x, shape, name=None):
    """Reshape via dense roundtrip (pattern changes arbitrarily —
    reference sparse/unary.py reshape does an index remap; on TPU the
    dense detour is the XLA-fusable form at these sizes)."""
    d = _dense(x).reshape(tuple(int(s) for s in shape))
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.fromdense(d))
    return SparseCooTensor(jsparse.BCOO.fromdense(d))


def slice(x, axes, starts, ends, name=None):
    import builtins

    d = _dense(x)
    idx = [builtins.slice(None)] * d.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[int(ax)] = builtins.slice(int(st), int(en))
    d = d[tuple(idx)]
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.fromdense(d))
    return SparseCooTensor(jsparse.BCOO.fromdense(d))


def mv(x, vec, name=None):
    """sparse [M, N] @ dense vector [N] -> dense [M] (reference:
    sparse/matmul.py mv)."""
    out = x._sp @ jnp.asarray(unwrap(vec))
    return wrap(out.todense() if hasattr(out, "todense") else out)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) (reference: sparse/matmul.py addmm)."""
    prod = _dense(x) @ _dense(y)
    return wrap(beta * _dense(input) + alpha * prod)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA of a sparse matrix via the dense linalg path
    (reference: sparse/multiary.py pca_lowrank)."""
    from .. import linalg as _linalg
    return _linalg.pca_lowrank(wrap(_dense(x)), q=q, center=center,
                               niter=niter)
