from .random import (Generator, default_generator, get_rng_state, next_key,  # noqa
                     rng_scope, seed, set_rng_state)
