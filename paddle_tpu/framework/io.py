"""Serialization: paddle.save / paddle.load parity
(reference: python/paddle/framework/io.py).

State dicts of Tensors are stored as pickled numpy arrays; nested containers
are preserved. Distributed (sharded) checkpointing lives in
distributed/checkpoint/."""
from __future__ import annotations

import pickle
from pathlib import Path

import numpy as np

from ..core.tensor import Tensor


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return {"__pt_tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_storable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__pt_tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(__import__("jax.numpy", fromlist=["asarray"]).asarray(obj["data"]),
                       stop_gradient=obj["stop_gradient"], name=obj.get("name"))
            return t
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_storable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4):
    """paddle.save parity."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, return_numpy=False):
    """paddle.load parity."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_storable(obj, return_numpy=return_numpy)
