"""Serialization: paddle.save / paddle.load parity
(reference: python/paddle/framework/io.py).

State dicts of Tensors are stored as pickled numpy arrays; nested containers
are preserved. Writes are atomic (staged next to the destination, then
``os.replace``d) so a crash mid-save can never truncate an existing
checkpoint. Distributed (sharded) checkpointing — including the commit
protocol and CheckpointManager — lives in distributed/checkpoint/."""
from __future__ import annotations

import os
import pickle
import threading
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return {"__pt_tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_storable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__pt_tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(jnp.asarray(obj["data"]),
                       stop_gradient=obj["stop_gradient"],
                       name=obj.get("name"))
            return t
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_storable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4):
    """paddle.save parity. Atomic: pickles into a same-directory temp
    file and ``os.replace``s it over ``path``, so a crash (or a raising
    ``__reduce__``) mid-write never truncates an existing checkpoint."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    # pid + thread id: a concurrent save of the same path from another
    # process or thread must not share the staging file
    tmp = p.parent / f"{p.name}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(_to_storable(obj), f, protocol=protocol)
        os.replace(tmp, p)
    finally:
        if tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass


def load(path, return_numpy=False):
    """paddle.load parity."""
    try:
        with open(path, "rb") as f:
            obj = pickle.load(f)
    except FileNotFoundError:
        raise
    except (pickle.UnpicklingError, EOFError, AttributeError,
            ImportError, IndexError, UnicodeDecodeError) as e:
        raise RuntimeError(
            f"paddle.load: failed to unpickle checkpoint at {str(path)!r} "
            f"({type(e).__name__}: {e}) — the file is truncated, corrupt, "
            "or not a paddle checkpoint") from e
    return _from_storable(obj, return_numpy=return_numpy)


# -- asynchronous save (reference: framework/io.py async_save /
# clear_async_save_task_queue). A small daemon-thread queue over save():
# the object is snapshotted to host numpy synchronously (consistent with
# training continuing to mutate params), the pickle+write runs in the
# background. ---------------------------------------------------------------
_ASYNC_TASKS: list = []
_ASYNC_MU = threading.Lock()        # guards the task list
_ASYNC_WRITE_MU = threading.Lock()  # serializes the actual writes


def _snapshot(obj):
    import jax

    def leaf(x):
        if hasattr(x, "_data"):
            return np.asarray(x._data)
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x
    return jax.tree.map(leaf, obj)


def async_save(obj, path, protocol=4, sync_other_task=False):
    """save() that returns immediately; the write happens on a
    background thread (device->host snapshot is taken synchronously so
    later param mutation can't corrupt the checkpoint)."""
    if sync_other_task:
        clear_async_save_task_queue()
    snap = _snapshot(obj)

    def run():
        # one write at a time: concurrent saves (same or different
        # paths) serialize instead of interleaving on a shared file
        with _ASYNC_WRITE_MU:
            save(snap, path, protocol)

    th = threading.Thread(target=run, daemon=True)
    with _ASYNC_MU:
        # prune finished writers here, not only in the drain call —
        # otherwise a long-lived trainer that never drains leaks one
        # dead Thread object per save. Start under the lock: an
        # unstarted thread reads as not-alive, so a concurrent prune
        # would silently drop it from the queue.
        _ASYNC_TASKS[:] = [t for t in _ASYNC_TASKS if t.is_alive()]
        th.start()
        _ASYNC_TASKS.append(th)
    return th


def clear_async_save_task_queue():
    """Block until every queued async_save has finished writing."""
    while True:
        with _ASYNC_MU:
            if not _ASYNC_TASKS:
                return
            th = _ASYNC_TASKS.pop()
        # join outside the lock: a writer appending concurrently (via
        # async_save) must not deadlock against a long join
        if th.is_alive():
            th.join()
