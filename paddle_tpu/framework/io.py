"""Serialization: paddle.save / paddle.load parity
(reference: python/paddle/framework/io.py).

State dicts of Tensors are stored as pickled numpy arrays; nested containers
are preserved. Distributed (sharded) checkpointing lives in
distributed/checkpoint/."""
from __future__ import annotations

import pickle
from pathlib import Path

import numpy as np

from ..core.tensor import Tensor


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return {"__pt_tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_storable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__pt_tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(__import__("jax.numpy", fromlist=["asarray"]).asarray(obj["data"]),
                       stop_gradient=obj["stop_gradient"], name=obj.get("name"))
            return t
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_storable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4):
    """paddle.save parity."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, return_numpy=False):
    """paddle.load parity."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_storable(obj, return_numpy=return_numpy)


# -- asynchronous save (reference: framework/io.py async_save /
# clear_async_save_task_queue). A small daemon-thread queue over save():
# the object is snapshotted to host numpy synchronously (consistent with
# training continuing to mutate params), the pickle+write runs in the
# background. ---------------------------------------------------------------
_ASYNC_TASKS: list = []
_ASYNC_LOCK = None   # created lazily (threading import stays local)


def _async_worker(snap, path, protocol):
    # atomic write: a crash/exit mid-pickle can never corrupt an
    # existing checkpoint at `path`
    import os
    tmp = f"{path}.tmp.{os.getpid()}"
    save(snap, tmp, protocol)
    os.replace(tmp, path)


def _snapshot(obj):
    import numpy as np
    import jax

    def leaf(x):
        if hasattr(x, "_data"):
            return np.asarray(x._data)
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x
    return jax.tree.map(leaf, obj)


def async_save(obj, path, protocol=4, sync_other_task=False):
    """save() that returns immediately; the write happens on a
    background thread (device->host snapshot is taken synchronously so
    later param mutation can't corrupt the checkpoint)."""
    import threading
    global _ASYNC_LOCK
    if _ASYNC_LOCK is None:
        _ASYNC_LOCK = threading.Lock()
    if sync_other_task:
        clear_async_save_task_queue()
    snap = _snapshot(obj)

    def run():
        # one write at a time: concurrent saves (same or different
        # paths) serialize instead of interleaving on a shared file
        with _ASYNC_LOCK:
            _async_worker(snap, path, protocol)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    _ASYNC_TASKS.append(th)
    return th


def clear_async_save_task_queue():
    """Block until every queued async_save has finished writing."""
    while _ASYNC_TASKS:
        th = _ASYNC_TASKS.pop()
        if th.is_alive():
            th.join()
