"""RNG state management.

Design: a global eager generator (paddle.seed parity) plus an explicit
``rng_scope`` for pure/jitted code — inside a scope, keys derive
deterministically from the scope key by ``fold_in`` on a trace-time counter,
so a jitted train step that takes a per-step key is fully functional (the
TPU-native replacement for the reference's per-device RNG state + the
RNGStatesTracker used for TP determinism,
python/paddle/distributed/fleet/layers/mpu/random.py:34)."""
from __future__ import annotations

import contextlib
import threading

import jax


class Generator:
    """Stateful key generator (eager mode). The key materializes lazily —
    building it at import time would initialize the XLA backend before
    jax.distributed.initialize can run (multi-process bring-up,
    distributed/env.py)."""

    def __init__(self, seed_: int = 0):
        self._key = None
        self._seed = seed_

    def manual_seed(self, s: int):
        self._key = jax.random.PRNGKey(s)
        self._seed = s
        return self

    def initial_seed(self):
        return self._seed

    def next_key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    def set_state(self, state):
        self._key = state


default_generator = Generator(0)


class _ScopeState(threading.local):
    def __init__(self):
        self.stack = []  # list of [key, counter]


_scopes = _ScopeState()


@contextlib.contextmanager
def rng_scope(key):
    """Pure RNG scope: all random ops inside draw keys derived from ``key``.
    Safe under jit tracing (counter advances at trace time, deterministically)."""
    _scopes.stack.append([key, 0])
    try:
        yield
    finally:
        _scopes.stack.pop()


def next_key():
    """Key for one random op: from the innermost rng_scope if present,
    else from the global eager generator."""
    if _scopes.stack:
        entry = _scopes.stack[-1]
        k = jax.random.fold_in(entry[0], entry[1])
        entry[1] += 1
        return k
    return default_generator.next_key()


def in_rng_scope() -> bool:
    return bool(_scopes.stack)


def seed(s: int):
    """paddle.seed parity: reseed the global generator."""
    default_generator.manual_seed(int(s))
    return default_generator


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(state):
    default_generator.set_state(state[0])
