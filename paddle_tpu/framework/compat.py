"""Top-level framework compatibility surface: places, dtype info,
print options, reader batching, FLOPs estimation, lazy init.

Reference capability: python/paddle/base/core places (phi::Place bindings),
python/paddle/framework/framework.py set_printoptions, python/paddle/batch.py,
python/paddle/hapi/dynamic_flops.py, python/paddle/nn/initializer/lazy_init.py.
TPU-native: places map onto jax devices (CPU host / TPU accelerator); FLOPs
estimation walks a traced jaxpr and counts dot/conv FLOPs analytically
instead of per-layer hooks.
"""
from __future__ import annotations

import contextlib

import jax
import numpy as np
from ..core import enforce as E


# -- places (reference: phi::CPUPlace / GPUPlace pybind) --------------------

class Place:
    """Device handle. Equality is by (kind, id) like the reference."""
    _kind = "undefined"

    def __init__(self, id: int = 0):
        self._id = int(id)

    def get_device_id(self) -> int:
        return self._id

    def __eq__(self, other):
        return (isinstance(other, Place) and self._kind == other._kind
                and self._id == other._id)

    def __hash__(self):
        return hash((self._kind, self._id))

    def __repr__(self):
        return f"Place({self._kind}:{self._id})"

    def jax_device(self):
        kind = "cpu" if self._kind == "cpu" else None
        devs = jax.devices(kind) if kind else jax.devices()
        return devs[min(self._id, len(devs) - 1)]


class CPUPlace(Place):
    _kind = "cpu"

    def __repr__(self):
        return "Place(cpu)"


class CUDAPlace(Place):
    """Accelerator place. On this framework the accelerator is the TPU;
    the CUDA name is kept for API-compatible checkpoint/config code."""
    _kind = "accelerator"


class CUDAPinnedPlace(Place):
    _kind = "cpu_pinned"

    def __repr__(self):
        return "Place(cpu_pinned)"


class TPUPlace(Place):
    _kind = "accelerator"


# -- dtype info -------------------------------------------------------------

def finfo(dtype):
    from ..core.dtype import convert_dtype
    return np.finfo(np.dtype(convert_dtype(dtype)))


def iinfo(dtype):
    from ..core.dtype import convert_dtype
    return np.iinfo(np.dtype(convert_dtype(dtype)))


# -- printing ---------------------------------------------------------------

def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr formatting (reference: framework.py set_printoptions).
    Tensor reprs render through numpy, so numpy's printoptions are the
    single source of truth."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# -- reader batching (reference: python/paddle/batch.py) --------------------

def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader."""
    if batch_size <= 0:
        raise E.InvalidArgumentError(f"batch_size must be positive, got {batch_size}")

    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


# -- FLOPs estimation (reference: hapi/dynamic_flops.py) --------------------

_FLOP_OPS = {"dot_general", "conv_general_dilated"}


def _jaxpr_flops(jaxpr) -> int:
    total = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "dot_general":
            dnums = eqn.params["dimension_numbers"]
            (lc, _), (lb, _) = dnums
            lhs = eqn.invars[0].aval.shape
            out = eqn.outvars[0].aval.shape
            k = int(np.prod([lhs[i] for i in lc])) if lc else 1
            total += 2 * int(np.prod(out, dtype=np.int64)) * k
        elif prim == "conv_general_dilated":
            rhs = eqn.invars[1].aval.shape
            out = eqn.outvars[0].aval.shape
            dn = eqn.params["dimension_numbers"]
            cout_idx = dn.out_spec[1]
            spatial = [s for i, s in enumerate(out)
                       if i not in (dn.out_spec[0], cout_idx)]
            cin_k = int(np.prod([rhs[i] for i in range(len(rhs))
                                 if i != dn.rhs_spec[0]], dtype=np.int64))
            total += 2 * int(np.prod(spatial, dtype=np.int64)) \
                * out[dn.out_spec[0]] * out[cout_idx] * cin_k // rhs[dn.rhs_spec[0]]
        for sub in eqn.params.values():
            if hasattr(sub, "jaxpr"):
                total += _jaxpr_flops(sub.jaxpr)
    return total


def flops(net, input_size, custom_ops=None, print_detail=False) -> int:
    """Analytic FLOPs of one forward pass (reference signature:
    hapi/dynamic_flops.py flops). Counts matmul/conv multiply-adds from
    the traced jaxpr — no per-layer hooks needed."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    def run(x):
        out = net(Tensor(x))
        return out._data if isinstance(out, Tensor) else out

    x = jnp.zeros(tuple(input_size), jnp.float32)
    closed = jax.make_jaxpr(run)(x)
    total = _jaxpr_flops(closed.jaxpr)
    if print_detail:
        print(f"Total FLOPs: {total}")
    return total


# -- lazy init (reference: nn/initializer/lazy_init.py LazyGuard) -----------

class LazyGuard:
    """Context manager deferring parameter materialisation. Under XLA
    param init is already lazy until jit execution, so the guard only
    needs to mark the scope (kept for API parity)."""
    _active = False

    def __enter__(self):
        type(self)._active = True
        return self

    def __exit__(self, *exc):
        type(self)._active = False
        return False


def disable_signal_handler():
    """Reference parity (pybind disable_signal_handler): the JAX runtime
    installs no catching handlers to remove — no-op."""


@contextlib.contextmanager
def _noop_ctx():
    yield
