"""paddle.audio.features parity (reference:
python/paddle/audio/features/layers.py — Spectrogram:24,
MelSpectrogram:106, LogMelSpectrogram:206, MFCC:309). Composed from the
stft op + fbank/DCT matmuls, all jit-able."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.base import Layer
from ..ops._op import unwrap, wrap
from ..ops.fft_ops import stft
from .functional import compute_fbank_matrix, create_dct, get_window, power_to_db


class Spectrogram(Layer):
    """reference: layers.py:24."""

    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window="hann", power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        mag = jnp.abs(unwrap(spec))
        if self.power != 1.0:
            mag = mag ** self.power
        return wrap(mag)


class MelSpectrogram(Layer):
    """reference: layers.py:106."""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window="hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm, dtype)

    def forward(self, x):
        spec = self._spectrogram(x)          # [..., freq, frames]
        mel = jnp.matmul(unwrap(self.fbank), unwrap(spec))
        return wrap(mel)


class LogMelSpectrogram(Layer):
    """reference: layers.py:206."""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window="hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db=None, dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    """reference: layers.py:309."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length=None, win_length=None, window="hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max=None, htk: bool = False,
                 norm="slaney", ref_value: float = 1.0, amin: float = 1e-10,
                 top_db=None, dtype: str = "float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct = create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)     # [..., n_mels, frames]
        mfcc = jnp.matmul(jnp.swapaxes(unwrap(self.dct), 0, 1),
                          unwrap(logmel))
        return wrap(mfcc)
