"""paddle.audio.backends parity: wav load/save (reference:
python/paddle/audio/backends/wave_backend.py — stdlib `wave`-based IO, the
same no-external-deps choice)."""
from __future__ import annotations

import wave as _wave

import numpy as np

from ..core.tensor import Tensor
from ..ops._op import unwrap, wrap
from ..core import enforce as E

__all__ = ["load", "save", "info", "list_available_backends",
           "get_current_backend", "set_backend"]

_backend = "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return _backend


def set_backend(name: str):
    global _backend
    if name not in list_available_backends():
        raise E.InvalidArgumentError(f"unknown audio backend {name!r}")
    _backend = name


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_frames = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath: str) -> AudioInfo:
    with _wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(), w.getnchannels(),
                         w.getsampwidth() * 8)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Returns (Tensor[channels, samples] float32 in [-1, 1], sample_rate)."""
    with _wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        nch = w.getnchannels()
        sw = w.getsampwidth()
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(n)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[sw]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, nch)
    if sw == 1:
        data = data.astype(np.int16) - 128
        scale = 128.0
    else:
        scale = float(2 ** (8 * sw - 1))
    out = data.astype(np.float32)
    if normalize:
        out = out / scale
    if channels_first:
        out = out.T
    return wrap(np.ascontiguousarray(out)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16):
    arr = np.asarray(unwrap(src) if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T
    if arr.ndim == 1:
        arr = arr[:, None]
    scaled = np.clip(arr, -1.0, 1.0) * (2 ** (bits_per_sample - 1) - 1)
    if bits_per_sample == 8:
        # 8-bit WAV is UNSIGNED PCM with a 128 offset
        pcm = (scaled + 128).astype(np.uint8)
    else:
        pcm = scaled.astype({16: np.int16,
                             32: np.int32}[bits_per_sample])
    with _wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[1])
        w.setsampwidth(bits_per_sample // 8)
        w.setframerate(sample_rate)
        w.writeframes(pcm.tobytes())
