"""paddle.audio.datasets parity: ESC50, TESS.

Reference capability: python/paddle/audio/datasets/{esc50,tess}.py —
download-and-parse audio classification datasets. No network egress here:
construction requires ``data_file=`` (ESC50: the extracted archive dir
with meta/esc50.csv + audio/; TESS: the extracted dir of
<emotion>/<name>.wav). Feature modes mirror the reference ('raw',
'mfcc', 'spectrogram', 'melspectrogram', 'logmelspectrogram')."""
from __future__ import annotations

import csv
import os

import numpy as np

from ..io.dataset import Dataset
from ..core import enforce as E

__all__ = ["ESC50", "TESS"]


def _need_dir(name, path):
    if path is None or not os.path.isdir(path):
        raise E.PreconditionNotMetError(
            f"{name}: automatic download is unavailable in this "
            "environment; pass data_file= pointing at the extracted "
            "dataset directory")
    return path


class _AudioClsDataset(Dataset):
    def __init__(self, feat_type="raw", **feat_kwargs):
        if feat_type not in ("raw", "mfcc", "spectrogram",
                             "melspectrogram", "logmelspectrogram"):
            raise E.InvalidArgumentError(f"unknown feat_type {feat_type!r}")
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        self._files = []     # (path, label)

    def _load_wave(self, path):
        from .backends import load

        wav, sr = load(path)
        return np.asarray(wav), sr

    def _extract(self, wav, sr):
        if self.feat_type == "raw":
            return wav.astype(np.float32)
        from ..core.tensor import Tensor
        from . import features

        x = Tensor(wav.reshape(1, -1).astype(np.float32))
        if self.feat_type == "mfcc":
            f = features.MFCC(sr=sr, **self.feat_kwargs)
        elif self.feat_type == "spectrogram":
            f = features.Spectrogram(**self.feat_kwargs)
        elif self.feat_type == "melspectrogram":
            f = features.MelSpectrogram(sr=sr, **self.feat_kwargs)
        else:
            f = features.LogMelSpectrogram(sr=sr, **self.feat_kwargs)
        return np.asarray(f(x).numpy())[0]

    def __getitem__(self, idx):
        path, label = self._files[idx]
        wav, sr = self._load_wave(path)
        return self._extract(wav, sr), np.int64(label)

    def __len__(self):
        return len(self._files)


class ESC50(_AudioClsDataset):
    """ESC-50 environmental sounds (reference: audio/datasets/esc50.py).
    5-fold split: mode='train' keeps folds != split, 'dev' keeps the
    split fold."""

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_file=None, archive=None, **feat_kwargs):
        super().__init__(feat_type, **feat_kwargs)
        root = _need_dir("ESC50", data_file)
        meta = os.path.join(root, "meta", "esc50.csv")
        if not os.path.exists(meta):
            raise E.PreconditionNotMetError(f"ESC50: missing meta file {meta}")
        with open(meta, newline="") as f:
            for row in csv.DictReader(f):
                fold = int(row["fold"])
                keep = (fold != split) if mode == "train" else (fold == split)
                if keep:
                    self._files.append(
                        (os.path.join(root, "audio", row["filename"]),
                         int(row["target"])))


class TESS(_AudioClsDataset):
    """Toronto emotional speech set (reference: audio/datasets/tess.py).
    Labels from the emotion directory names; n_folds split by index."""

    _EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral",
                 "ps", "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_file=None, archive=None, **feat_kwargs):
        super().__init__(feat_type, **feat_kwargs)
        root = _need_dir("TESS", data_file)
        all_files = []
        for dirpath, _, names in sorted(os.walk(root)):
            for name in sorted(names):
                if not name.lower().endswith(".wav"):
                    continue
                low = name.lower()
                label = None
                for i, emo in enumerate(self._EMOTIONS):
                    if emo in low:
                        label = i
                        break
                if label is not None:
                    all_files.append((os.path.join(dirpath, name), label))
        for i, item in enumerate(all_files):
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                self._files.append(item)
