"""paddle.audio.functional parity (reference:
python/paddle/audio/functional/functional.py + window.py). All pure jnp —
fbank/DCT matrices are precomputed host-side constants applied via matmul
(MXU-friendly), exactly how the reference composes them."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..ops._op import unwrap, wrap
from ..core import enforce as E

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "power_to_db", "create_dct", "get_window",
]


def _maybe_tensor(x, out):
    from ..core.tensor import Tensor
    return wrap(out) if isinstance(x, Tensor) else float(out) \
        if np.ndim(out) == 0 else wrap(out)


def hz_to_mel(freq, htk: bool = False):
    """reference: functional.py:24 (slaney by default, htk optional)."""
    from ..core.tensor import Tensor
    f = unwrap(freq) if isinstance(freq, Tensor) else freq
    f = jnp.asarray(f, jnp.float32)
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                              / min_log_hz) / logstep, mel)
    return _maybe_tensor(freq, mel)


def mel_to_hz(mel, htk: bool = False):
    """reference: functional.py:80."""
    from ..core.tensor import Tensor
    m = unwrap(mel) if isinstance(mel, Tensor) else mel
    m = jnp.asarray(m, jnp.float32)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = jnp.where(m >= min_log_mel,
                       min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                       hz)
    return _maybe_tensor(mel, hz)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32"):
    lo = unwrap(hz_to_mel(f_min, htk))
    hi = unwrap(hz_to_mel(f_max, htk))
    mels = jnp.linspace(lo, hi, n_mels)
    return wrap(unwrap(mel_to_hz(wrap(mels), htk)).astype(dtype))


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    return wrap(jnp.linspace(0, float(sr) / 2, 1 + n_fft // 2,
                             dtype=dtype))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm="slaney", dtype="float32"):
    """reference: functional.py:188 — [n_mels, 1 + n_fft//2] triangles."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = unwrap(fft_frequencies(sr, n_fft, "float32"))
    melfreqs = unwrap(mel_frequencies(n_mels + 2, f_min, f_max, htk,
                                      "float32"))
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return wrap(weights.astype(dtype))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db=80.0):
    """reference: functional.py:261."""
    s = unwrap(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return wrap(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm="ortho", dtype="float32"):
    """reference: functional.py:305 — [n_mels, n_mfcc] DCT-II basis."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(math.sqrt(1.0 / (4.0 * n_mels)))
        dct = dct.at[:, 1:].multiply(math.sqrt(1.0 / (2.0 * n_mels)))
    return wrap(dct.astype(dtype))


def get_window(window, win_length: int, fftbins: bool = True,
               dtype="float32"):
    """reference: window.py get_window (hann/hamming/blackman/...)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length if fftbins else win_length - 1
    i = np.arange(win_length)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * i / n)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * i / n)
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * i / n)
             + 0.08 * np.cos(4 * np.pi * i / n))
    elif name == "bartlett":
        w = 1.0 - np.abs(2.0 * i / n - 1.0)
    elif name in ("rect", "boxcar", "ones"):
        w = np.ones(win_length)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((i - n / 2.0) / std) ** 2)
    elif name == "triang":
        w = 1.0 - np.abs((i - n / 2.0) / ((win_length + 1) / 2.0))
    else:
        raise E.InvalidArgumentError(f"unsupported window {name!r}")
    return wrap(jnp.asarray(w.astype(dtype)))
