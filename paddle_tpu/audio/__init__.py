"""paddle.audio parity (reference: python/paddle/audio/__init__.py)."""
from . import backends  # noqa
from . import features  # noqa
from . import functional  # noqa
from .backends import load, save, info  # noqa

from . import datasets  # noqa

__all__ = ["backends", "features", "functional", "load", "save", "info",
           "datasets"]
