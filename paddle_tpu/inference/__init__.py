"""paddle.inference parity: Config + Predictor over saved artifacts.

Reference capability: paddle/fluid/inference/api/analysis_predictor.h:100
(AnalysisPredictor) and python/paddle/inference/wrapper.py — the deploy
surface: load a serialized program + weights in a fresh process, bind
named inputs, run, read named outputs. TPU-native redesign: the artifact
is the hermetic StableHLO program written by paddle.jit.save (or
static.save_inference_model); "analysis passes" are XLA's compile
pipeline, so Config's IR-optimization knobs are accepted for parity and
delegated. No separate C++ predictor runtime is needed — XLA's runtime is
the native engine under the same API shape.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from ..core import enforce as E
from ..core import jax_compat as _jax_compat  # noqa: F401  (jax.export shim)

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "PrecisionType", "PlaceType", "get_version",
           "EngineOverloaded",
           "PageAllocator", "PagedKVCache", "Request", "RequestCost",
           "RequestOutput", "RequestRejected", "ServingEngine"]

_SERVING = {"PageAllocator": "paged", "PagedKVCache": "paged",
            "EngineOverloaded": "engine",
            "Request": "engine", "RequestCost": "engine",
            "RequestOutput": "engine", "RequestRejected": "engine",
            "ServingEngine": "engine"}


def __getattr__(name):
    # Lazy: the serving stack pulls in the model families; the static
    # Predictor surface must stay importable without them (and without
    # a circular import during package init).
    if name in _SERVING:
        import importlib
        mod = importlib.import_module(f".{_SERVING[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def get_version() -> str:
    from .. import __version__
    return __version__


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM = "custom"


class Config:
    """reference: inference/api/paddle_analysis_config.h (AnalysisConfig).
    Points at a saved artifact prefix; device/optimization toggles are
    accepted and recorded (XLA owns them)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle passes either (model_dir) or (prog_file, params_file);
        # artifacts here are a single prefix (prefix.pdmodel + ...)
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._device = "tpu"
        self._device_id = 0
        self._ir_optim = True
        self._glog_info = False
        self._memory_optim = True

    def set_prog_file(self, path: str):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def prog_file(self):
        return self._prefix

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device, self._device_id = "gpu", device_id

    def enable_custom_device(self, device_type, device_id=0):
        self._device, self._device_id = device_type, device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "gpu"

    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag=True):
        self._memory_optim = bool(flag)

    def disable_glog_info(self):
        self._glog_info = False

    def set_cpu_math_library_num_threads(self, n):
        pass

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix}, device={self._device}, "
                f"ir_optim={self._ir_optim})")


class Tensor:
    """Named IO handle (reference: inference/api/paddle_tensor.h
    ZeroCopyTensor) — copy_from_cpu / copy_to_cpu semantics."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, data: np.ndarray):
        if not self._is_input:
            raise E.PreconditionNotMetError("copy_from_cpu on an output handle")
        self._owner._inputs[self.name] = jnp.asarray(np.asarray(data))

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise E.PreconditionNotMetError("copy_to_cpu on an input handle")
        out = self._owner._outputs.get(self.name)
        if out is None:
            raise E.PreconditionNotMetError("run() the predictor before reading outputs")
        return np.asarray(out)

    def shape(self):
        if self._is_input:
            arr = self._owner._inputs.get(self.name)
        else:
            arr = self._owner._outputs.get(self.name)
        return list(arr.shape) if arr is not None else None

    def reshape(self, shape):
        pass  # shapes are taken from the fed arrays


class Predictor:
    """reference: analysis_predictor.h:100. Wraps a jit.save /
    save_inference_model artifact; run() executes the compiled program."""

    def __init__(self, config: Config):
        self.config = config
        prefix = config.prog_file()
        if prefix is None or not os.path.exists(prefix + ".pdmodel"):
            raise FileNotFoundError(
                f"no saved program at {prefix}.pdmodel")
        import pickle
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax.export.deserialize(f.read())
        meta_path = prefix + ".pdmeta"
        self._meta = {}
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                self._meta = pickle.load(f)
        # two artifact flavors: jit.save (params in .pdiparams, inputs are
        # positional) or static.save_inference_model (named feeds)
        self._kind = "static" if "feed_names" in self._meta else "jit"
        if self._kind == "static":
            self._input_names = list(self._meta["feed_names"])
            self._output_names = list(self._meta["fetch_names"])
            self._params = None
            self._buffers = None
            self._out_tree = None
        else:
            from ..framework.io import load as fload
            blob = fload(prefix + ".pdiparams")
            from ..core.tensor import Tensor as PTensor
            self._params = {n: (p._data if isinstance(p, PTensor)
                                else jnp.asarray(np.asarray(p)))
                            for n, p in blob["params"].items()}
            self._buffers = {n: (b._data if isinstance(b, PTensor)
                                 else jnp.asarray(np.asarray(b)))
                             for n, b in blob["buffers"].items()}
            n_in = int(self._meta.get("n_inputs", 1))
            self._input_names = [f"x{i}" for i in range(n_in)]
            self._output_names = None   # known after first run
        self._inputs: Dict[str, jax.Array] = {}
        self._outputs: Dict[str, jax.Array] = {}

    # -- IO surface --------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        if self._output_names is None:
            return [f"out{i}" for i in range(len(self._outputs) or 1)]
        return list(self._output_names)

    def get_input_handle(self, name: str) -> Tensor:
        if name not in self._input_names:
            raise KeyError(f"unknown input {name!r}; "
                           f"inputs are {self._input_names}")
        return Tensor(name, self, is_input=True)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=False)

    # -- execution ---------------------------------------------------------
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Either bind handles then run(), or pass arrays positionally
        (both reference calling conventions)."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n] = jnp.asarray(np.asarray(a))
        missing = [n for n in self._input_names if n not in self._inputs]
        if missing:
            raise E.PreconditionNotMetError(f"inputs not set: {missing}")
        args = [self._inputs[n] for n in self._input_names]
        if self._kind == "static":
            flat = self._exported.call(*args)
        else:
            flat = self._exported.call(self._params, self._buffers, *args)
        flat = list(flat) if isinstance(flat, (tuple, list)) else [flat]
        if self._output_names is None:
            self._output_names = [f"out{i}" for i in range(len(flat))]
        self._outputs = dict(zip(self._output_names, flat))
        if inputs is not None:
            return [np.asarray(o) for o in flat]
        return True

    def clear_intermediate_tensor(self):
        self._outputs.clear()


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_inference_api.h CreatePredictor."""
    return Predictor(config)
