"""Continuous-batching serving engine over the paged KV cache.

Reference capability: the vLLM/TGI scheduler loop (and the reference's
fastdeploy serving stack) — a request queue feeding a fixed grid of
decode slots, admission gated on free KV pages, prefill-then-join so a
new request enters the running batch without draining it, retirement
freeing pages the moment a sequence finishes — rebuilt TPU-native:

- The decode data plane is ONE jitted program over the static
  ``[num_slots]`` grid (paged_decode_step + vectorised sampling inside
  a ``lax.scan`` of ``decode_chunk`` steps), so continuous batching
  never retraces: joins/retires only permute host-side block tables
  between chunks. One device round-trip per chunk, not per token.
- Admission policy: a request is admitted when a slot is free AND the
  pool keeps >= ``watermark`` free pages after its prompt allocation —
  the page headroom that lets RUNNING requests keep appending without
  immediate preemption.
- Preemption: when a running request cannot get its next page, the
  youngest running request is evicted (pages freed, request requeued
  for full recomputation — the vLLM "recompute" policy, the right
  choice when sequences are short relative to prefill cost).
- Per-step slot compaction: retirements compact the active slots to the
  low indices before each admission pass, so occupancy accounting and
  the admission scan touch a dense prefix.

Instrumentation (paddle_tpu.monitor, FLAGS_enable_monitor-gated):
``serving.pages.in_use|total``, ``serving.batch.occupancy``,
``serving.queue.depth`` gauges; ``serving.requests.admitted|completed|
preempted``, ``serving.tokens.generated|prefilled|discarded`` counters.
The same numbers are always available unconditionally on
``engine.stats``.

SLO latency (monitor-gated, one cached-flag branch when off): each
request's lifecycle is stamped enqueue -> admit -> prefill -> first
token -> retire, feeding the ``serving.latency.*`` histograms —
``queue_wait_ms`` (latest enqueue to admission; a preempted request
re-queues and waits again — each wait observed once, while the
per-request cost record keeps the CUMULATIVE sum), ``ttft_ms``
(ORIGINAL enqueue to the
prefill-sampled first token of the run the client KEEPS — observed
once per request at retirement, so a preempted run's discarded first
token never biases the histogram),
``tpot_ms`` (mean inter-token time over the decode phase, chunk-edge
resolution), ``e2e_ms`` (original enqueue to retire). All carry
bucket-interpolated p50/p90/p95/p99 in their snapshots. The same
milestones land in the ``monitor.trace`` ring as lifecycle events, so
a flight record shows which requests were in flight at a crash.

Token accounting contract (pinned by tests/test_trace.py):
``serving.tokens.generated`` counts every SAMPLED token (prefill's
first token + decode emissions — work done, including work later
thrown away); ``serving.tokens.discarded`` counts tokens a preemption
discarded for recompute. On a drained engine
``generated - discarded == sum(len(output.tokens))`` exactly.

Cost attribution (monitor-gated, PR 12): requests carry a ``tenant``
(default ``"default"``) and ``priority``, validated/coerced at submit
with the rest of the isolation screening, and every request
accumulates a :class:`RequestCost` record across its lifecycle —
prefill/decode/discarded tokens, CUMULATIVE queue wait across
preemption re-queues (the ``queue_wait_ms`` histogram still observes
each individual wait once), page-seconds (pages held x wall,
integrated at the chunk boundaries the emitted-grid download already
synchronizes — the cost plane adds ZERO device synchronizations at
any rate), slot steps + occupancy share, and modeled FLOPs (the
chunk/prefill program's registered cost-analysis FLOPs from
``monitor/programs.py``, split evenly across the live slots/group
rows that shared the dispatch). The record rides out on
``RequestOutput.cost`` and folds into ``monitor/slo.py``'s windowed
SLO accounting + bounded per-tenant aggregates at retirement; each
scheduler step also feeds the autoscale tick
(``slo.note_sched_tick``). Monitor off: ``cost`` is None and none of
this exists — byte-identical emitted tokens either way.

Overload control (PR 13, the ACTING half of ROADMAP item 5 — all
flag-gated, every flag default OFF, flags-off scheduling byte-identical
to the accounting-only engine; see docs/overload.md):

- **Priority admission** (``FLAGS_serving_priority_admission``): the
  admission scan orders the queue by (priority desc, arrival) and
  enforces ``FLAGS_serving_tenant_inflight_cap`` live slots per tenant.
- **Bounded queue + shedding** (``FLAGS_serving_max_queue``,
  ``FLAGS_serving_shed_on_burn``): a full queue — or an SLO
  fast-burn, for priority<=0 work — sheds submissions with a typed
  :class:`EngineOverloaded` carrying a ``retry_after_s`` hint from the
  autoscale demand model; a higher-priority arrival displaces the
  lowest-priority queued request instead.
- **Deadlines** (per-request ``Request.deadline_s``, default off):
  a spent TTL expires the request in queue or evicts it from the
  running batch (partial tokens delivered, ``finish_reason="expired"``,
  cost recorded).
- **SLO-aware preemption** (``FLAGS_serving_slo_preemption``): page
  pressure evicts the lowest-(priority, prior preemptions, accumulated
  work) request instead of youngest-first.
- **Drain lifecycle** (:meth:`ServingEngine.begin_drain`): stop
  admitting, shed the queue with retry hints, finish live decodes;
  ``drain_complete`` gates the elastic controller's scale-in
  (``distributed/fleet/elastic.py``).

Every submitted request ends in exactly one of completed / rejected /
expired / shed, with a typed reason — nothing is dropped silently.
"""
from __future__ import annotations

import dataclasses
import math
import time
import weakref
from collections import deque
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from ..core import enforce as E
from ..monitor import profile_capture as _pcap
from ..monitor import server as _mserver
from ..monitor import trace as _trace
from ..monitor import slo as _slo
from ..monitor import forensics as _forensics
from ..monitor.registry import LATENCY_BUCKETS_MS as _LATENCY_BUCKETS_MS
from .paged import (PagedKVCache, PrefixCache, paged_decode_step,
                    paged_prefill, paged_prefill_shared,
                    paged_verify_window)


def _engine_health_provider(ref):
    """``/healthz`` contributor over a weakly-held engine: queue depth,
    slot occupancy, page-pool pressure. Returns None once the engine is
    garbage-collected (the server prunes the entry). Always ``ok`` —
    a deep queue is backpressure, not a liveness failure."""
    def provide():
        eng = ref()
        if eng is None:
            return None
        return {
            "ok": True,
            "queue_depth": len(eng.queue),
            "slots_live": sum(1 for s in eng.slots if s is not None),
            "num_slots": eng.num_slots,
            "pages_free": eng.cache.alloc.free_pages,
            "pages_total": eng.cache.num_pages,
            "requests_completed": eng.stats.completed,
        }
    return provide

def _observe_latency(name: str, ms: float, doc: str):
    _monitor.observe(name, ms, doc=doc, buckets=_LATENCY_BUCKETS_MS)

__all__ = ["EngineOverloaded", "Request", "RequestCost", "RequestOutput",
           "RequestRejected", "ServingEngine"]


class RequestRejected(E.InvalidArgumentError):
    """A malformed submission, refused at the door.

    Raised by :meth:`ServingEngine.submit` BEFORE the request touches
    the queue, the page pool, or any device state — so one client's
    garbage (oversized prompt, empty prompt, non-finite temperature,
    out-of-vocab token ids) can never detonate mid-chunk and take down
    the engine loop for every other in-flight request. Counted under
    ``serving.requests.rejected``. Subclasses the framework's
    InvalidArgumentError (and therefore ValueError), so existing typed
    handlers keep working."""

    def __init__(self, rid, reason: str):
        self.rid = rid
        self.reason = reason
        super().__init__(f"request {rid!r} rejected: {reason}")


class EngineOverloaded(RequestRejected):
    """Backpressure: a WELL-FORMED submission refused by overload
    policy — bounded queue full (``FLAGS_serving_max_queue``), SLO
    fast-burn shedding (``FLAGS_serving_shed_on_burn``), or a draining
    replica. Unlike its malformed-submission parent this is not the
    client's fault: ``retry_after_s`` carries a hint computed from the
    autoscale demand model (``monitor/slo.retry_after_hint`` over this
    engine's own state), so the caller can back off or retry on
    another replica. Counted under ``serving.requests.shed``."""

    def __init__(self, rid, reason: str, retry_after_s: float):
        self.retry_after_s = retry_after_s
        super().__init__(rid, reason)


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray                   # [S] int32 token ids
    max_new_tokens: int
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    key: Optional[jax.Array] = None      # PRNG key when temperature > 0
    tenant: str = "default"              # cost-attribution dimension
    priority: int = 0                    # scheduling class: HIGHER is
    #                                      more important (admission
    #                                      order, shed exemption,
    #                                      preemption protection)
    deadline_s: Optional[float] = None   # TTL from submit; the request
    #                                      expires in queue or is
    #                                      evicted from the running
    #                                      batch once it is spent
    #                                      (default off)
    prompt_spec: Optional[dict] = None   # failover journal only: a
    #                                      derivation spec (trace seed,
    #                                      rid, lengths) the admission
    #                                      journal records INSTEAD of
    #                                      inline prompt tokens, so a
    #                                      re-dispatch rebuilds the
    #                                      exact prompt as a pure
    #                                      function of the spec


@dataclasses.dataclass
class RequestCost:
    """Per-request resource attribution, accumulated at the engine's
    existing host-sync seams (monitor-gated; see the module
    docstring). Cumulative across preemption re-queues — the record
    follows the REQUEST, not one run of it."""

    tenant: str = "default"
    priority: int = 0
    prefill_tokens: int = 0      # prompt tokens prefilled (re-prefills
    #                              after preemption included; tokens a
    #                              cached prefix skipped are NOT here —
    #                              they were not work done)
    prefix_cached_tokens: int = 0    # prompt tokens served from the
    #                              radix prefix cache instead of
    #                              prefill (cumulative across re-runs)
    prefill_flops_saved: float = 0.0  # modeled FLOPs the cached prefix
    #                              skipped (tail program's registered
    #                              per-padded-token rate x cached)
    decode_tokens: int = 0       # decode emissions (work done, incl.
    #                              tokens a preemption later discarded)
    discarded_tokens: int = 0    # thrown away by preemption recompute
    queue_wait_ms: float = 0.0   # SUM of every enqueue->admission wait
    page_seconds: float = 0.0    # KV pages held x wall (chunk edges)
    slot_steps: int = 0          # decode-grid steps a slot was held
    grid_steps: int = 0          # grid capacity (steps x slots) that
    #                              elapsed during the residencies
    slot_share: Optional[float] = None   # slot_steps / grid_steps
    model_flops: float = 0.0     # registered program FLOPs, split
    #                              across the dispatch's live slots
    preemptions: int = 0
    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    e2e_ms: Optional[float] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class RequestOutput:
    rid: int
    tokens: np.ndarray                   # generated ids (<= max_new_tokens)
    prompt_len: int
    preemptions: int = 0                 # times this request was evicted
    tenant: str = "default"
    cost: Optional[RequestCost] = None   # monitor on: the attribution
    #                                      record; monitor off: None
    finish_reason: str = "completed"     # completed | expired | shed —
    #                                      every request that entered
    #                                      the engine ends in exactly
    #                                      one (rejected submissions
    #                                      never enter)
    retry_after_s: Optional[float] = None  # shed only: demand-model
    #                                      backoff hint
    shed_reason: Optional[str] = None    # shed only: the typed policy
    #                                      reason (displacement /
    #                                      drain) — what submit-time
    #                                      sheds carry on the
    #                                      EngineOverloaded they raise


class _Slot:
    __slots__ = ("req", "kv_len", "gen", "tokens", "pending", "done",
                 "keys", "preemptions", "t_first", "t_last",
                 "cost", "t_tick", "steps0", "ng", "ng_n")

    def __init__(self, req: Request, keys: np.ndarray):
        self.req = req
        self.kv_len = 0          # KV positions written (prompt + decoded)
        self.gen = 0             # tokens sampled so far
        self.tokens: List[int] = []
        self.pending = 0         # last sampled token (KV not yet written)
        self.done = False
        self.keys = keys         # [max_new, 2] uint32 sampling keys
        self.preemptions = 0
        self.t_first = None      # first-token wall stamp (monitor on)
        self.t_last = None       # latest-token wall stamp (monitor on)
        self.cost = None         # the request's RequestCost (monitor on)
        self.t_tick = None       # last page-seconds integration stamp
        self.steps0 = 0          # engine decode_steps at admission
        self.ng = None           # spec decode: bigram draft table over
        #                          this request's own context (lazy)
        self.ng_n = 0            # context tokens folded into ng so far


class EngineStats:
    def __init__(self):
        self.admitted = 0
        self.completed = 0
        self.preempted = 0
        self.expired = 0         # retired by their submit-time deadline
        self.shed = 0            # refused/ended by overload policy
        self.decode_steps = 0
        self.tokens_generated = 0    # incl. the token sampled at prefill
        self.tokens_decoded = 0      # emitted by decode steps only
        self.tokens_prefilled = 0
        self.tokens_discarded = 0    # thrown away by preemption recompute
        self.peak_pages_in_use = 0
        self._occ_steps = 0      # decode steps weighted by slot count
        # shared-prefix radix cache (FLAGS_serving_prefix_cache)
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0     # prompt tokens not re-prefilled
        self.prefix_evictions = 0        # radix nodes dropped by pressure
        # n-gram speculative decode (FLAGS_serving_spec_decode)
        self.spec_rounds = 0     # per-slot verify windows dispatched
        self.spec_drafted = 0    # draft tokens proposed (C-1 per round)
        self.spec_accepted = 0   # drafts accepted by greedy verify

    def occupancy(self) -> float:
        """Useful-token fraction of the decode grid: decode-emitted
        tokens / (decode steps x slots). Empty slots, done-masked chunk
        tails and drain phases all count against it — the honest
        number."""
        return (self.tokens_decoded / self._occ_steps
                if self._occ_steps else 0.0)

    def as_dict(self) -> dict:
        return {"admitted": self.admitted, "completed": self.completed,
                "preempted": self.preempted,
                "expired": self.expired, "shed": self.shed,
                "decode_steps": self.decode_steps,
                "tokens_generated": self.tokens_generated,
                "tokens_prefilled": self.tokens_prefilled,
                "tokens_discarded": self.tokens_discarded,
                "peak_pages_in_use": self.peak_pages_in_use,
                "batch_occupancy": round(self.occupancy(), 4),
                "prefix_lookups": self.prefix_lookups,
                "prefix_hits": self.prefix_hits,
                "prefix_tokens_saved": self.prefix_tokens_saved,
                "prefix_evictions": self.prefix_evictions,
                "spec_rounds": self.spec_rounds,
                "spec_drafted": self.spec_drafted,
                "spec_accepted": self.spec_accepted}


def _sample_rows(logits, temps, keys, sampled=True):
    """Vectorised per-slot sampling: greedy rows where temperature is 0,
    else categorical on the tempered logits with that slot's own key —
    row-for-row the same draw the ring-buffer ``generate`` makes, so
    fixed-seed parity holds. ``sampled=False`` (every live slot greedy)
    skips the threefry/gumbel draw entirely — per-token RNG is real
    money at small model sizes."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not sampled:
        return greedy
    drawn = jax.vmap(lambda row, t, k: jax.random.categorical(
        k, row / jnp.maximum(t, 1e-6)))(logits, temps, keys)
    return jnp.where(temps > 0, drawn.astype(jnp.int32), greedy)


def _decode_chunk(family, config, chunk, sampled, params, pool_k, pool_v,
                  block_tables, tokens, kv_len, done, gen, keys, temps,
                  max_new, eos):
    """``chunk`` decode steps as one program: write the pending token's
    KV, attend, sample the next. Done slots coast (writes dropped via
    length 0, outputs masked to -1)."""

    def body(carry, key_t):
        pool_k, pool_v, tok, kvl, done, gen = carry
        n = jnp.where(done, 0, kvl + 1)
        pool_k, pool_v, logits = paged_decode_step(
            family, params, pool_k, pool_v, block_tables, n, tok, config)
        kvl = jnp.where(done, kvl, kvl + 1)
        nxt = _sample_rows(logits, temps, key_t, sampled)
        emitted = jnp.where(done, -1, nxt)
        gen = gen + jnp.where(done, 0, 1)
        hit_eos = (~done) & (nxt == eos)
        done = done | hit_eos | (gen >= max_new)
        tok = jnp.where(emitted >= 0, nxt, tok)
        return (pool_k, pool_v, tok, kvl, done, gen), emitted

    (pool_k, pool_v, tok, kvl, done, gen), emitted = jax.lax.scan(
        body, (pool_k, pool_v, tokens, kv_len, done, gen), keys,
        length=chunk)
    return pool_k, pool_v, tok, kvl, done, gen, emitted


class ServingEngine:
    """Continuous-batching decode over a paged KV cache.

    ``family`` is a model module exposing the decoder seam
    (models.llama / models.moe); ``params`` may be the bf16 tree or the
    weight-only int8 tree from ``family.quantize_weights``."""

    def __init__(self, family, params, config, *, num_slots: int = 8,
                 max_len: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 decode_chunk: int = 4, watermark: float = 0.0,
                 kv_dtype=None, kv_quant: Optional[bool] = None,
                 priority_admission: Optional[bool] = None,
                 tenant_inflight_cap: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 shed_on_burn: Optional[bool] = None,
                 slo_preemption: Optional[bool] = None,
                 failover: Optional[bool] = None,
                 prefix_cache: Optional[bool] = None,
                 spec_decode: Optional[bool] = None):
        # Overload policies (ROADMAP item 5, acting half). Each kwarg
        # defaults to its FLAGS_serving_* flag (the make_train_step
        # guard=None pattern); every flag defaults OFF, and with all of
        # them off the scheduler is byte-identical to the pre-policy
        # engine — the existing parity tests are the contract.
        from ..core import flags as _eflags

        def _opt(v, flag):
            return _eflags.flag_value(flag) if v is None else v
        self._priority_admission = bool(
            _opt(priority_admission, "serving_priority_admission"))
        # negatives clamp to 0 = uncapped/unbounded (the "-1 means
        # unlimited" convention; a raw -1 cap would read `0 >= -1` for
        # every tenant and block admission forever)
        self._tenant_cap = max(0, int(
            _opt(tenant_inflight_cap, "serving_tenant_inflight_cap")))
        self._max_queue = max(0, int(
            _opt(max_queue, "serving_max_queue")))
        self._shed_on_burn = bool(
            _opt(shed_on_burn, "serving_shed_on_burn"))
        self._slo_preemption = bool(
            _opt(slo_preemption, "serving_slo_preemption"))
        # Exactly-once failover (inference/failover.py): the flag only
        # OFFERS durability — journaling starts when a controller (or
        # test) calls attach_journal, the publish_frames opt-in shape.
        # Flag off and unattached: one None check per terminal event.
        self._failover = bool(_opt(failover, "serving_failover"))
        # Per-token-latency optimizations (ROADMAP item 2): both
        # default off; flags-off scheduling and emitted tokens are
        # byte-identical (the parity tests pin it). The PrefixCache
        # itself is created after the page pool below.
        self._prefix_on = bool(_opt(prefix_cache, "serving_prefix_cache"))
        self._spec_decode = bool(_opt(spec_decode, "serving_spec_decode"))
        # Quantized memory plane (ROADMAP perf item): int8 page pools
        # with per-page per-kv-head scale planes. Off = full-precision
        # pools, byte-identical contents and tokens.
        self._kv_quant = bool(_opt(kv_quant, "serving_kv_quant"))
        self._journal = None
        self._draining = False
        self._deadlines_seen = False   # sticky: first deadline request
        #                                arms the per-step expiry scan
        self.family = family
        self.params = params
        self.config = config
        self.num_slots = int(num_slots)
        self.decode_chunk = int(decode_chunk)
        E.enforce(self.decode_chunk >= 1, "decode_chunk must be >= 1")
        max_len = int(max_len if max_len is not None
                      else config.max_position_embeddings)
        kv_dtype = kv_dtype if kv_dtype is not None else config.dtype
        if page_size is None:
            from ..kernels import autotune as _at
            page_size = _at.paged_page_size(
                num_slots, config.num_attention_heads,
                config.num_key_value_heads, config.head_dim,
                -(-max_len // 16) * 16, kv_dtype,
                kv_quant=self._kv_quant)
        self.page_size = int(page_size)
        self.max_len = -(-max_len // self.page_size) * self.page_size
        self.max_pages_per_seq = self.max_len // self.page_size
        if num_pages is None:
            num_pages = self.num_slots * self.max_pages_per_seq
        E.enforce(num_pages >= self.max_pages_per_seq,
                  f"pool of {num_pages} pages cannot hold even one "
                  f"max-length sequence ({self.max_pages_per_seq} pages)")
        self.watermark_pages = int(watermark * num_pages)
        self.cache = PagedKVCache(config, num_pages, self.page_size,
                                  self.max_pages_per_seq, kv_dtype,
                                  kv_quant=self._kv_quant)
        # radix shared-prefix cache over the pool's committed pages;
        # None (flag off) short-circuits every hook to the original code
        self._prefix = PrefixCache(self.cache.alloc) if self._prefix_on \
            else None
        self.queue: deque = deque()
        self.slots: List[Optional[_Slot]] = [None] * self.num_slots
        self.outputs: Dict[int, RequestOutput] = {}
        self.stats = EngineStats()
        self._rng_fallback = 0

        self._prefill_fns: dict = {}     # (S_pad, sampled) -> jitted
        # shared-prefix tail prefills keyed by (g, S_tail, ctx_pages,
        # sampled); spec verify windows keyed by chunk length
        self._prefill_shared_fns: dict = {}
        self._spec_fns: dict = {}
        # chunk programs keyed by (length, sampled): greedy-only skips
        # per-token RNG; the 4x "turbo" length engages when every live
        # slot is guaranteed to run it end-to-end (no retire/join could
        # happen mid-chunk), quartering per-chunk host+dispatch overhead
        # through the long middle of large generations
        self.turbo_chunk = self.decode_chunk * 4
        self._chunk_fns = {
            (c, s): jax.jit(partial(_decode_chunk, family, config, c, s),
                            donate_argnums=(1, 2))
            for c in (self.decode_chunk, self.turbo_chunk)
            for s in (False, True)}
        # KV-page absmax sampling (monitor/numerics.py): 1-in-N decode
        # chunks dispatch a tiny per-layer per-page |K|/|V| max over
        # the pool AFTER the chunk's emitted-grid download has already
        # synchronized the device — zero added block_until_ready calls
        # at any rate (PR 9's pattern, pinned by test)
        self._kv_chunks = 0
        self._kv_absmax_fn = None
        # Fleet SLO federation (monitor/federation.py): an attached
        # FramePublisher rides the per-scheduler-step host tick — one
        # None check per step when unattached, pure host reads when
        # attached (zero added device synchronizations at any rate)
        self._frame_pub = None
        # registered-program FLOPs, cached per registry key: the cost
        # plane reads it once per chunk, not once per slot, and the
        # cached value keeps the per-dispatch cost at one dict lookup
        self._flops_by_key: dict = {}
        # device-side slot state, reused across chunks until a
        # join/retire/preempt (state) or page-table change (bt) dirties it
        self._dev: dict = {}
        self._state_dirty = True
        self._bt_dirty = True
        self._sampled = False
        self._zero_keys = {
            c: jnp.zeros((c, self.num_slots, 2), jnp.uint32)
            for c in (self.decode_chunk, self.turbo_chunk)}
        _monitor.set_gauge("serving.pages.total",
                           self.cache.num_pages,
                           doc="KV page pool capacity")
        # Operator plane: start the telemetry server when its flag is
        # set (one cached branch otherwise) and contribute this
        # engine's scheduler state to /healthz. The provider holds the
        # engine WEAKLY — a retired engine prunes itself, never pins —
        # and registers only while some plane could read it (monitor on
        # or server flag/running): a fully-off process must not grow
        # the provider map one entry per engine, ever.
        # Process-unique uid (GIL-atomic counter, monitor/programs.py)
        # keys both the /healthz provider name ("serving:<n>" — two
        # engines must not evict each other's view) and the
        # introspection-registry records (which outlive the engine —
        # id(self) reuse must not alias a successor onto stale ones).
        _mserver.maybe_start()
        self._engine_uid = _monitor.programs.next_uid()
        if _monitor.enabled() or _mserver.plane_active():
            _mserver.register_health_provider(
                f"serving:{self._engine_uid}",
                _engine_health_provider(weakref.ref(self)))
        # Sharding inspector (distributed/introspect.py): the param
        # tree's per-leaf layout for /sharding — pure serving runs
        # populate the view with no training loop in sight. Self-gated
        # on the monitor flag (off path computes + registers nothing).
        from ..distributed import introspect as _introspect
        _introspect.register_sharded_tree(
            f"serving:{self._engine_uid}.params", self.params)

    def _record_serving_program(self, spec_key, name, jitted, args,
                                kwargs, donated=()):
        """Register a serving program with the introspection registry
        (monitor/programs.py) once per specialization — signature,
        donation map, cost-analysis FLOPs (one re-trace), and a lazy
        memory analyzer the ``/programs`` endpoint resolves. The
        registry ITSELF is the dedup (not an engine-local set): after
        a ``monitor.reset()`` mid-run the next dispatch re-registers,
        so the scrape endpoints and the headroom estimate's temp
        reservation recover instead of staying empty forever. The
        per-dispatch cost after the first is one locked dict lookup,
        monitor-on only. The params sharding tree rides the same
        reset-recovery seam (ensure_sharded_tree): a mid-run
        ``monitor.reset()`` repopulates ``/sharding`` on the next
        dispatch, like the program registry itself."""
        from ..distributed import introspect as _introspect
        from ..monitor import programs as _programs
        _introspect.ensure_sharded_tree(
            f"serving:{self._engine_uid}.params", lambda: self.params)
        key = ("engine", self._engine_uid) + spec_key
        if _programs.has_record(key):
            _programs.note_hit(key)
            return key
        _programs.record_jit_call(key, name, jitted, args,
                                  kwargs=kwargs, source="serving",
                                  donated=donated)
        return key

    def _program_flops(self, key):
        """Cached ``monitor/programs.flops_of`` read (None when the
        backend never reported a count). An unknown key is NOT cached
        as None: a ``monitor.reset()`` mid-run re-registers on the
        next dispatch and the lookup must recover with it."""
        v = self._flops_by_key.get(key)
        if v is None:
            from ..monitor import programs as _programs
            v = _programs.flops_of(key)
            if v is not None:
                self._flops_by_key[key] = v
        return v

    # -- submission ---------------------------------------------------------

    def _reject_reason(self, req: Request):
        """``(why this submission must be refused, None)``, or
        ``(None, (prompt ndarray, max_new int, temperature float))``
        when it is well-formed — the validated+coerced values ride back
        and submit writes them ONTO the request, so a coercible-but-
        wrong-typed field (temperature="0.7", max_new_tokens=2.9) can
        never pass screening here and still detonate later in the
        scheduler. Every check runs on the HOST copy before the request
        touches any engine state — anything that would otherwise raise
        inside a compiled prefill/decode chunk (and kill the loop for
        every in-flight request) is turned into a rejection here
        instead."""
        def bad(reason):
            return reason, None
        try:
            prompt = np.asarray(req.prompt)
        except Exception:
            return bad("prompt is not array-like")
        if prompt.ndim != 1:
            return bad(f"prompt must be 1-D token ids, got shape "
                       f"{prompt.shape}")
        plen = int(prompt.shape[0])
        if plen < 1:
            return bad("empty prompt")
        if not np.issubdtype(prompt.dtype, np.integer):
            return bad(f"prompt dtype {prompt.dtype} is not an integer "
                       "token-id type")
        vocab = int(self.config.vocab_size)
        lo, hi = int(prompt.min()), int(prompt.max())
        if lo < 0 or hi >= vocab:
            return bad(f"prompt token ids outside [0, {vocab}): min {lo}, "
                       f"max {hi}")
        try:
            max_new = int(req.max_new_tokens)
            if max_new != req.max_new_tokens:   # 2.9 must not pass as 2
                return bad(f"max_new_tokens {req.max_new_tokens!r} is "
                           "not an integral count")
        except (TypeError, ValueError, OverflowError):
            # OverflowError: int(float('inf')) — must reject typed,
            # not crash the caller
            return bad(f"max_new_tokens {req.max_new_tokens!r} is not "
                       "an int")
        if max_new < 1:
            return bad(f"max_new_tokens must be >= 1, got {max_new}")
        if plen + max_new > self.max_len:
            return bad(f"prompt {plen} + max_new {max_new} exceeds "
                       f"max_len {self.max_len}")
        try:
            temp = float(req.temperature)
        except (TypeError, ValueError):
            return bad(f"temperature {req.temperature!r} is not a float")
        if not math.isfinite(temp) or temp < 0.0:
            return bad(f"temperature must be finite and >= 0, got {temp}")
        tenant = req.tenant
        if tenant is None:
            tenant = "default"
        else:
            try:
                tenant = str(tenant)
            except Exception:
                return bad("tenant is not string-coercible")
            tenant = tenant or "default"
            # content is NOT restricted — exposition escapes hostile
            # bytes and the slo plane bounds cardinality — but a label
            # value is not a document
            if len(tenant) > 128:
                return bad(f"tenant name of {len(tenant)} chars exceeds "
                           "the 128-char limit")
        try:
            priority = int(req.priority)
            if priority != req.priority:     # 1.5 must not pass as 1
                return bad(f"priority {req.priority!r} is not an "
                           "integral class")
        except (TypeError, ValueError, OverflowError):
            return bad(f"priority {req.priority!r} is not an int")
        deadline = req.deadline_s
        if deadline is not None:
            try:
                deadline = float(deadline)
            except (TypeError, ValueError, OverflowError):
                # OverflowError: float(10**400) — reject typed, don't
                # crash the caller (the max_new_tokens precedent)
                return bad(f"deadline_s {req.deadline_s!r} is not a "
                           "float")
            if not math.isfinite(deadline) or deadline <= 0.0:
                return bad("deadline_s must be finite and > 0, got "
                           f"{deadline}")
        return None, (prompt, max_new, temp, tenant, priority, deadline)

    def submit(self, req: Request):
        """Queue a request, or raise :class:`RequestRejected` (typed,
        counted) when it is malformed — the engine and every in-flight
        request are untouched either way until admission. With the
        overload policies on (all default-off), a well-formed
        submission may instead be SHED with :class:`EngineOverloaded`
        (typed, counted, ``retry_after_s`` hint): the queue is bounded
        (``max_queue``), an SLO fast-burn sheds priority<=0 work
        (``shed_on_burn``), and a draining replica refuses everything.
        A higher-priority submission arriving at a full queue displaces
        the lowest strictly-lower-priority queued request instead (the
        displaced one ends in ``outputs`` with
        ``finish_reason="shed"``)."""
        reason, norm = self._reject_reason(req)
        if reason is not None:
            _monitor.inc("serving.requests.rejected",
                         doc="malformed submissions refused at the "
                             "door (engine state untouched)")
            _trace.instant("serving.reject", rid=req.rid, reason=reason)
            if _monitor.enabled():
                # availability = non-rejected fraction: the refusal
                # must enter the SLO window, attributed to whatever
                # tenant the submission claimed (best-effort — the
                # rejection may be ABOUT the tenant field)
                try:
                    tenant = str(req.tenant or "default")[:128]
                except Exception:
                    tenant = "default"
                _slo.record_rejected(tenant or "default")
                _forensics.note_terminal(req.rid, "rejected",
                                         reason=reason,
                                         tenant=tenant or "default")
            raise RequestRejected(req.rid, reason)
        # the scheduler consumes the NORMALIZED values it was screened
        # on — the original coercible-but-wrong-typed fields must not
        # ride into the loop
        (req.prompt, req.max_new_tokens, req.temperature,
         req.tenant, req.priority, req.deadline_s) = norm
        if getattr(req, "_submitted", False):
            # re-admission of a previously-submitted object (the client
            # kept it): per-run mutable state must not carry over — the
            # cost record restarts, TTFT/e2e re-anchor, a stale
            # deadline anchor must not expire the new run, and the
            # preemption count is the new run's. (Preemption re-queues
            # re-enter via appendleft, not submit, and deliberately
            # keep all of it — the record follows the request across
            # ONE run.) The PRNG key is the exception: _keys_for pinned
            # the first run's key onto req.key, so a resubmission
            # replays byte-identical tokens.
            req._t0 = None
            req._t_enqueue = None
            req._cost = None
            req._t_deadline = None
            req._preempt_count = 0
        # overload gates, in severity order: a draining replica refuses
        # everything; an SLO fast-burn sheds best-effort work; a full
        # bounded queue sheds (or displaces for higher priority). All
        # three raise BEFORE the request touches any engine state.
        if self._draining:
            self._shed_submit(req, "engine is draining")
        if (self._shed_on_burn and req.priority <= 0
                and _monitor.enabled()
                and _slo.burn_alerting(load_only=True)):
            # load_only: the trigger reads the LATENCY burn — the
            # sheds this gate produces are availability-bad records,
            # and feeding them back would lock best-effort traffic
            # out long after the real overload cleared
            self._shed_submit(req, "SLO fast-burn alerting; "
                                   "priority<=0 work shed")
        if self._max_queue and len(self.queue) >= self._max_queue:
            victim = self._displaceable_pos(req.priority)
            if victim is None:
                self._shed_submit(
                    req, f"queue full ({self._max_queue}) and no "
                         f"lower-priority request to displace")
            else:
                shed = self.queue[victim]
                del self.queue[victim]
                _forensics.decision(
                    "displace", rid=shed.rid, reason="queue_full",
                    queue_depth=len(self.queue) + 1,
                    max_queue=self._max_queue, by_rid=req.rid,
                    by_priority=req.priority,
                    victim_priority=getattr(shed, "priority", 0))
                self._finish_shed(
                    shed, "displaced by higher-priority request "
                          f"{req.rid!r}")
        if req.deadline_s is not None:
            req._t_deadline = time.perf_counter() + req.deadline_s
            self._deadlines_seen = True
        plen = int(req.prompt.shape[0])
        if _monitor.enabled():
            now = time.perf_counter()
            # t0 anchors TTFT/e2e (first submission wins); t_enqueue is
            # refreshed by preemption re-queues and anchors queue_wait
            req._t0 = getattr(req, "_t0", None) or now
            req._t_enqueue = now
            # the cost record follows the REQUEST across preemption
            # re-queues (they re-enter via appendleft, not submit —
            # but a client resubmitting the same object keeps it too)
            if getattr(req, "_cost", None) is None:
                req._cost = RequestCost(tenant=req.tenant,
                                        priority=req.priority)
            _trace.instant("serving.enqueue", rid=req.rid, prompt=plen,
                           max_new=req.max_new_tokens,
                           tenant=req.tenant)
            _forensics.note(req.rid, "enqueue", t=now,
                            tenant=req.tenant, priority=req.priority,
                            prompt=plen, max_new=req.max_new_tokens)
        req._submitted = True
        if self._journal is not None:
            # journal AFTER every gate that could still refuse the
            # request (a shed/rejected submission never entered the
            # engine and must not be re-dispatched), and pin the
            # sampling key BEFORE the record is written so a
            # re-dispatch replays byte-identical tokens
            if req.temperature > 0.0 and req.key is None:
                self._rng_fallback += 1
                req.key = jax.random.PRNGKey(self._rng_fallback)
            self._journal.admit(req)
        self.queue.append(req)

    # -- overload policy: shedding, deadlines, drain ------------------------

    def autoscale_payload(self) -> dict:
        """The autoscale demand model (``monitor/slo.demand_model``)
        over THIS engine's state — works with the monitor off (shedding
        needs a ``retry_after_s`` hint regardless), and is the
        per-replica signal the elastic serving controller consumes.
        Slots count as live while RESIDENT (done-but-unretired
        included): a finished request's output only materializes at
        the next ``step``'s retire, so ``drain_safe`` here matches
        :attr:`drain_complete` — a controller acting on it can never
        stop a replica while an output is still trapped in a slot.
        (The ``serving.autoscale.*`` gauges tick inside ``step`` after
        retirement, where the two notions coincide.)"""
        resident = sum(1 for s in self.slots if s is not None)
        return _slo.demand_model(
            len(self.queue), resident, self.num_slots,
            self.cache.alloc.free_pages / self.cache.num_pages
            if self.cache.num_pages else 0.0)

    def _retry_after(self) -> float:
        return _slo.retry_after_hint(self.autoscale_payload())

    def publish_frames(self, name: str, dir_path: Optional[str] = None,
                       *, min_interval_s: float = 0.25, client=None,
                       local_only: bool = False, slo_fn=None):
        """Opt this replica into fleet SLO federation
        (``monitor/federation.py``): attach a frame publisher that
        emits a compact versioned telemetry frame — autoscale payload,
        per-objective burn/compliance, bounded tenant aggregates,
        request terminal-state counters, drain state — on the existing
        per-scheduler-step host tick, through the name-keyed heartbeat
        transport (``dir_path`` file beats + coordination-service KV;
        the frame IS the liveness beat). Pure host reads; zero added
        device synchronizations at any publish rate. Returns the
        publisher (one per engine; re-attaching replaces it)."""
        from ..monitor import federation as _fed
        self._frame_pub = _fed.FramePublisher(
            name, dir_path=dir_path, client=client,
            local_only=local_only,
            min_interval_s=min_interval_s, slo_fn=slo_fn)
        self._frame_pub.maybe_publish(self, force=True)
        return self._frame_pub

    def attach_journal(self, name: str, dir_path: Optional[str] = None,
                       *, client=None):
        """Opt this replica into the exactly-once admission journal
        (``inference/failover.py``; requires ``failover=True`` /
        ``FLAGS_serving_failover`` — the flag gates the durability
        layer, this call names the replica and the transport). Every
        subsequent admission is journaled write-through and every
        terminal event writes a completion marker, so the elastic
        controller can re-dispatch work stranded by a crash without
        ever double-serving a finished request. Returns the journal
        (one per engine; re-attaching replaces it)."""
        if not self._failover:
            return None
        from .failover import AdmissionJournal
        self._journal = AdmissionJournal(name, dir_path=dir_path,
                                         client=client)
        return self._journal

    def _shed_submit(self, req: Request, why: str):
        """Refuse a WELL-FORMED submission by overload policy: typed
        :class:`EngineOverloaded` with the demand-model backoff hint,
        before the request touches any engine state."""
        hint = self._retry_after()
        self.stats.shed += 1
        _monitor.inc("serving.requests.shed",
                     doc="admissible work refused by overload policy "
                         "(bounded queue, SLO burn, displacement, "
                         "drain) with a retry_after_s hint")
        tenant = getattr(req, "tenant", "default") or "default"
        _trace.instant("serving.shed", rid=req.rid, reason=why,
                       retry_after_s=hint, tenant=tenant)
        if _monitor.enabled():
            _slo.record_shed(tenant)
            _forensics.decision("shed", rid=req.rid, reason=why,
                                queue_depth=len(self.queue),
                                priority=getattr(req, "priority", 0),
                                draining=self._draining)
            _forensics.note_terminal(req.rid, "shed", reason=why,
                                     tenant=tenant,
                                     retry_after_s=round(hint, 3))
        raise EngineOverloaded(req.rid, why, hint)

    def _displaceable_pos(self, priority: int) -> Optional[int]:
        """Queue position of the displacement victim for an arriving
        ``priority`` request at a full queue: the LOWEST-priority
        queued request, oldest first, and only when strictly below the
        newcomer — equal-priority work is never displaced (FIFO
        fairness within a class). Preemption re-queues are EXEMPT:
        they are admitted work mid-recompute, and admitted work is
        never dropped (the begin_drain contract) — a newcomer, however
        important, outranks only work that has not been served yet."""
        pos, lowest = None, None
        for j, r in enumerate(self.queue):
            if getattr(r, "_preempt_count", 0) > 0:
                continue
            p = getattr(r, "priority", 0)
            if p < priority and (lowest is None or p < lowest):
                pos, lowest = j, p
        return pos

    def _finish_shed(self, req: Request, why: str):
        """End a QUEUED request as shed (displacement or drain): it
        leaves through ``outputs`` with ``finish_reason="shed"`` and
        the backoff hint — never silently dropped (its submitter
        already returned from ``submit``)."""
        hint = self._retry_after()
        self.stats.shed += 1
        _monitor.inc("serving.requests.shed")
        mon = _monitor.enabled()
        cost = getattr(req, "_cost", None) if mon else None
        if cost is not None:
            t_enq = getattr(req, "_t_enqueue", None)
            if t_enq is not None:
                cost.queue_wait_ms += (time.perf_counter() - t_enq) * 1e3
        if mon:
            if cost is not None:
                # the shed rides availability like a rejection, but
                # its consumption (prefill before a preemption,
                # page-seconds, the queue wait above) folds into the
                # tenant aggregates — the tenant PAID for it
                _slo.record_request(dict(cost.as_dict(),
                                         rejected=True, shed=True))
            else:
                _slo.record_shed(getattr(req, "tenant", "default")
                                 or "default")
        self.outputs[req.rid] = RequestOutput(
            rid=req.rid, tokens=np.zeros(0, np.int32),
            prompt_len=int(np.asarray(req.prompt).shape[0]),
            preemptions=getattr(req, "_preempt_count", 0),
            tenant=getattr(req, "tenant", "default"),
            cost=cost, finish_reason="shed", retry_after_s=hint,
            shed_reason=why)
        if self._journal is not None:
            self._journal.finish(req.rid, "shed")
        tenant = getattr(req, "tenant", "default") or "default"
        _trace.instant("serving.shed", rid=req.rid, reason=why,
                       retry_after_s=hint, tenant=tenant)
        if mon:
            _forensics.decision("shed", rid=req.rid, reason=why,
                                queued=True,
                                priority=getattr(req, "priority", 0),
                                draining=self._draining)
            _forensics.note_terminal(req.rid, "shed", reason=why,
                                     tenant=tenant,
                                     retry_after_s=round(hint, 3))

    def begin_drain(self, shed_queued: bool = True):
        """Enter the drain lifecycle: stop admitting new work (submit
        sheds with ``EngineOverloaded``), shed the not-yet-admitted
        queue (``shed_queued=False`` lets it finish instead), and let
        live decodes run to retirement — ``drain_complete`` flips once
        nothing is queued or resident. A preemption during drain still
        re-queues for recompute (finishing live work may require it);
        only NEW submissions are refused. Idempotent."""
        from ..testing import faults as _faults
        _faults.hit("serving.drain")
        already = self._draining
        self._draining = True
        _trace.instant("serving.drain.begin", queued=len(self.queue),
                       again=already)
        if shed_queued:
            keep: deque = deque()
            while self.queue:
                r = self.queue.popleft()
                if getattr(r, "_preempt_count", 0) > 0:
                    # a preemption re-queue is ADMITTED live work
                    # awaiting recompute — the drain contract finishes
                    # it. This also makes repeat begin_drain calls
                    # (the elastic controller retries every tick)
                    # safe: after the first call, only preemption
                    # re-queues can enter the queue.
                    keep.append(r)
                else:
                    self._finish_shed(r, "engine is draining")
            self.queue = keep
        if self._frame_pub is not None:
            # drain state must reach the federation controller now,
            # not a rate-limit later — but only the TRANSITION forces:
            # the controller re-invokes begin_drain every retry tick
            # of a slow drain, and forcing each call would bypass the
            # rate limit into per-tick transport I/O
            self._frame_pub.maybe_publish(self, force=not already)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drain_complete(self) -> bool:
        """No queued and no resident requests (done-but-unretired slots
        count as resident: their outputs only materialize at the next
        ``step``)."""
        return not self.queue and all(s is None for s in self.slots)

    def _expire_due(self):
        """Deadline/TTL enforcement: retire every request whose
        submit-time deadline is spent — queued requests leave with no
        tokens, running ones are evicted with the tokens they had
        (pages freed, counted in the cost record). Runs once per
        ``step`` and only after some request has carried a deadline
        (``_deadlines_seen`` — deadline-free serving never pays the
        scan). A DONE slot past its deadline retires normally: its
        output is complete."""
        now = time.perf_counter()
        if self.queue and any(
                getattr(r, "_t_deadline", None) is not None
                and now >= r._t_deadline for r in self.queue):
            keep = deque()
            for r in self.queue:
                t = getattr(r, "_t_deadline", None)
                if t is not None and now >= t:
                    self._finish_expired(r, slot_idx=None, now=now)
                else:
                    keep.append(r)
            self.queue = keep
        for idx in range(self.num_slots):
            slot = self.slots[idx]
            if slot is None or slot.done:
                continue
            t = getattr(slot.req, "_t_deadline", None)
            if t is not None and now >= t:
                self._finish_expired(slot.req, slot_idx=idx, now=now)

    def _finish_expired(self, req: Request, slot_idx: Optional[int],
                        now: float):
        """End ``req`` as deadline-expired: from the queue (no tokens)
        or evicted from a running slot (partial tokens delivered —
        they were sampled and are the client's to keep, so the
        generated-discarded==emitted token contract holds)."""
        mon = _monitor.enabled()
        cost = getattr(req, "_cost", None) if mon else None
        tokens = np.zeros(0, np.int32)
        preemptions = getattr(req, "_preempt_count", 0)
        if slot_idx is not None:
            slot = self.slots[slot_idx]
            self.slots[slot_idx] = None
            self._state_dirty = self._bt_dirty = True
            if cost is not None and slot.t_tick is not None:
                # final page-seconds tick, read before the free
                cost.page_seconds += (
                    self.cache.alloc.page_count(req.rid)
                    * (now - slot.t_tick))
            self.cache.alloc.free(req.rid)
            tokens = np.asarray(slot.tokens, np.int32)
            preemptions = slot.preemptions
            if cost is not None:
                cost.grid_steps += (self.stats.decode_steps
                                    - slot.steps0) * self.num_slots
        elif cost is not None:
            t_enq = getattr(req, "_t_enqueue", None)
            if t_enq is not None:
                cost.queue_wait_ms += (now - t_enq) * 1e3
        self.stats.expired += 1
        _monitor.inc("serving.requests.expired",
                     doc="requests retired by their submit-time "
                         "deadline (expired in queue or evicted from "
                         "the running batch)")
        if cost is not None:
            cost.preemptions = preemptions
            t0 = getattr(req, "_t0", None)
            if t0 is not None:
                cost.e2e_ms = (now - t0) * 1e3
            if cost.grid_steps > 0:
                cost.slot_share = round(
                    cost.slot_steps / cost.grid_steps, 6)
            # the SLO window counts an expiry BAD for availability and
            # excludes it from the latency objectives (monitor/slo.py)
            _slo.record_request(dict(cost.as_dict(), expired=True))
        self.outputs[req.rid] = RequestOutput(
            rid=req.rid, tokens=tokens,
            prompt_len=int(np.asarray(req.prompt).shape[0]),
            preemptions=preemptions,
            tenant=getattr(req, "tenant", "default"),
            cost=cost, finish_reason="expired")
        if self._journal is not None:
            self._journal.finish(req.rid, "expired",
                                 tokens=int(tokens.shape[0]))
        tenant = getattr(req, "tenant", "default") or "default"
        _trace.instant("serving.expire", rid=req.rid,
                       tokens=int(tokens.shape[0]),
                       in_slot=slot_idx is not None, tenant=tenant)
        if mon:
            if slot_idx is not None:
                _forensics.decision("evict", rid=req.rid,
                                    reason="deadline", slot=slot_idx,
                                    tokens=int(tokens.shape[0]))
            _forensics.note_terminal(
                req.rid, "expired", t=now,
                e2e_ms=(cost.e2e_ms if cost is not None
                        and cost.e2e_ms else None),
                tenant=tenant, tokens=int(tokens.shape[0]),
                in_slot=slot_idx is not None)

    # -- scheduling ---------------------------------------------------------

    def _bucket(self, plen: int) -> int:
        """Padded prompt length: next power-of-two page count (bounds the
        number of distinct prefill compiles at log2(max_pages))."""
        pages = self.cache.alloc.pages_for(plen)
        b = 1
        while b < pages:
            b *= 2
        return min(b, self.max_pages_per_seq) * self.page_size

    def _prefill_fn(self, g: int, s_pad: int, sampled: bool):
        fn = self._prefill_fns.get((g, s_pad, sampled))
        if fn is None:
            family, config = self.family, self.config

            def _pf(params, ids, pool_k, pool_v, page_rows, slen, temp,
                    key):
                pk, pv, logits = paged_prefill(family, params, ids,
                                               config, pool_k, pool_v,
                                               page_rows, slen)
                # the first tokens sample INSIDE the prefill program —
                # one dispatch per admission GROUP, not two per request
                tok = _sample_rows(logits, temp, key, sampled)
                return pk, pv, tok

            fn = jax.jit(_pf, donate_argnums=(2, 3))
            self._prefill_fns[(g, s_pad, sampled)] = fn
        return fn

    def _prefill_shared_fn(self, g: int, s_eff: int, ncp: int,
                           sampled: bool):
        """Tail-only prefill over ``ncp`` cached prefix pages: same
        sample-inside-the-program contract as ``_prefill_fn``, one
        compile per (group, tail, ctx-pages, sampled) specialization
        (ctx length is page-bucketed like the tail, so the key space
        stays log-bounded)."""
        fn = self._prefill_shared_fns.get((g, s_eff, ncp, sampled))
        if fn is None:
            family, config = self.family, self.config

            def _pf(params, ids, pool_k, pool_v, page_rows, slen, temp,
                    key, ctx_rows):
                pk, pv, logits = paged_prefill_shared(
                    family, params, ids, config, pool_k, pool_v,
                    page_rows, slen, ctx_rows)
                tok = _sample_rows(logits, temp, key, sampled)
                return pk, pv, tok

            fn = jax.jit(_pf, donate_argnums=(2, 3))
            self._prefill_shared_fns[(g, s_eff, ncp, sampled)] = fn
        return fn

    def _spec_fn(self, C: int):
        """Greedy verify window for speculative decode: one program
        per chunk length, argmax inside (the host only ever needs the
        predicted ids)."""
        fn = self._spec_fns.get(C)
        if fn is None:
            family, config = self.family, self.config

            def _vf(params, pool_k, pool_v, bt, drafts, kv_len, live):
                pk, pv, logits = paged_verify_window(
                    family, params, drafts, config, pool_k, pool_v,
                    bt, kv_len, live)
                return pk, pv, jnp.argmax(
                    logits, axis=-1).astype(jnp.int32)

            fn = jax.jit(_vf, donate_argnums=(1, 2))
            self._spec_fns[C] = fn
        return fn

    def _free_slack(self) -> int:
        """Free pages the admission watermark may count: the free list
        plus prefix-cache pages reclaimable on demand (one
        ``_evict_pages`` away from free) — cold cache entries must
        never jam admission. Flag off: exactly ``free_pages``."""
        free = self.cache.alloc.free_pages
        if self._prefix is not None:
            free += self._prefix.reclaimable()
        return free

    def _evict_pages(self, n: int) -> int:
        """LRU-evict prefix-cache entries until ``n`` pages hit the
        free list (or nothing evictable remains); returns pages freed.
        Flag off: a no-op 0."""
        if self._prefix is None:
            return 0
        before = self._prefix.evicted_nodes
        freed = self._prefix.evict(n)
        dropped = self._prefix.evicted_nodes - before
        if dropped:
            self.stats.prefix_evictions += dropped
            _monitor.inc("serving.prefix_cache.evictions", dropped,
                         doc="radix nodes dropped under pool pressure")
        return freed

    def _match_len(self, req: Request) -> int:
        """Cached page-aligned prefix length for a prompt (group-fill
        compatibility probe; refreshes matched nodes' LRU stamps)."""
        return self._prefix.match(np.asarray(req.prompt))[0]

    def _alloc_for(self, req: Request, s_pad: int):
        """Admission allocation through the radix prefix cache: fork
        the longest cached page-aligned prefix by refcount and take
        only the tail fresh, evicting LRU cache leaves under pool
        pressure. The match is re-run after every eviction round —
        eviction may drop the very nodes just matched, and a stale
        pages list must never be forked. Stamps ``req._pfx_cached``
        with the shared token count on success. Flag off: the original
        ``alloc`` call, byte-identical."""
        alloc = self.cache.alloc
        if self._prefix is None:
            return alloc.alloc(req.rid, s_pad)
        self.stats.prefix_lookups += 1
        _monitor.inc("serving.prefix_cache.lookups",
                     doc="admission prompt-prefix radix probes")
        need = alloc.pages_for(s_pad)
        while True:
            cached, pages = self._prefix.match(np.asarray(req.prompt))
            missing = (need - len(pages)) - alloc.free_pages
            if missing > 0:
                if self._evict_pages(missing) == 0:
                    return None
                continue
            got = alloc.alloc_prefix(req.rid, pages, s_pad) if cached \
                else alloc.alloc(req.rid, s_pad)
            if got is None:
                return None
            req._pfx_cached = cached
            if cached:
                self.stats.prefix_hits += 1
                self.stats.prefix_tokens_saved += cached
                _monitor.inc("serving.prefix_cache.hits",
                             doc="admissions that forked cached "
                                 "prefix pages")
                _monitor.inc("serving.prefix_cache.tokens_saved", cached,
                             doc="prompt tokens served from cached KV "
                                 "instead of prefill")
            return got

    def _keys_for(self, req: Request) -> np.ndarray:
        if req.temperature <= 0.0:
            return np.zeros((req.max_new_tokens, 2), np.uint32)
        key = req.key
        if key is None:
            self._rng_fallback += 1
            key = jax.random.PRNGKey(self._rng_fallback)
            # pin the fallback onto the request: a resubmission of the
            # same object (and a failover re-dispatch reading it from
            # the journal) replays byte-identical tokens instead of
            # drawing a fresh counter key
            req.key = key
        return np.asarray(jax.random.split(key, req.max_new_tokens),
                          np.uint32)

    def _compact(self):
        """Slot compaction: pack live slots into the low indices (block
        tables and device slot state are rebuilt on the next chunk, so
        this is a pure host permutation)."""
        live = [s for s in self.slots if s is not None]
        packed = live + [None] * (self.num_slots - len(live))
        if packed != self.slots:
            self.slots = packed
            self._state_dirty = self._bt_dirty = True

    def _retire(self, idx: int):
        slot = self.slots[idx]
        self.slots[idx] = None
        self._state_dirty = self._bt_dirty = True
        mon = _monitor.enabled()
        cost = slot.cost if mon else None
        if cost is not None and slot.t_tick is not None:
            # final page-seconds tick: pages held from the last chunk
            # edge until this retirement, read BEFORE the free below
            now_t = time.perf_counter()
            cost.page_seconds += (
                self.cache.alloc.page_count(slot.req.rid)
                * (now_t - slot.t_tick))
            slot.t_tick = now_t
        if self._prefix is not None and slot.kv_len >= self.page_size:
            # retirement insertion: only COMMITTED positions enter the
            # radix — the prompt plus the generated tokens whose KV is
            # already written (kv_len worth; the final pending token's
            # KV never was). insert() takes a cache hold on each newly
            # shared page BEFORE the free below, so the pages survive
            # the sequence's release with ref >= 1.
            prompt = np.asarray(slot.req.prompt, np.int32)
            plen = int(prompt.shape[0])
            gen_committed = slot.kv_len - plen
            stream = prompt if gen_committed <= 0 else np.concatenate(
                [prompt, np.asarray(slot.tokens[:gen_committed],
                                    np.int32)])
            self._prefix.insert(stream,
                                self.cache.alloc.seq_pages(slot.req.rid))
        self.cache.alloc.free(slot.req.rid)
        self.outputs[slot.req.rid] = RequestOutput(
            rid=slot.req.rid,
            tokens=np.asarray(slot.tokens, np.int32),
            prompt_len=int(np.asarray(slot.req.prompt).shape[0]),
            preemptions=slot.preemptions,
            tenant=getattr(slot.req, "tenant", "default"),
            cost=cost)
        if self._journal is not None:
            # the completion marker lands BEFORE the output can be
            # harvested: a crash after this point re-dispatches
            # nothing for this rid (exactly-once dedup)
            self._journal.finish(slot.req.rid, "completed",
                                 tokens=int(len(slot.tokens)))
        self.stats.completed += 1
        _monitor.inc("serving.requests.completed")
        if mon:
            now = time.perf_counter()
            t0 = getattr(slot.req, "_t0", None)
            if t0 is not None:
                e2e = (now - t0) * 1e3
                _observe_latency(
                    "serving.latency.e2e_ms", e2e,
                    "request lifetime: original enqueue to retirement")
                if cost is not None:
                    cost.e2e_ms = e2e
                if slot.t_first is not None:
                    # observed at retirement, not at prefill: a
                    # preempted request re-prefills, and only the
                    # surviving run's first token — the one the client
                    # keeps — counts. One sample per completed request.
                    ttft = (slot.t_first - t0) * 1e3
                    _observe_latency(
                        "serving.latency.ttft_ms", ttft,
                        "original enqueue to the prefill-sampled "
                        "first token the client keeps")
                    if cost is not None:
                        cost.ttft_ms = ttft
            if slot.gen > 1 and slot.t_first is not None \
                    and slot.t_last is not None:
                # mean inter-token time over the decode phase; t_last
                # is the arrival of the final emitted token (chunk-edge
                # resolution), t_first the prefill-sampled token
                tpot = (slot.t_last - slot.t_first) / (slot.gen - 1) * 1e3
                _observe_latency(
                    "serving.latency.tpot_ms", tpot,
                    "mean time per output token after the first")
                if cost is not None:
                    cost.tpot_ms = tpot
            if cost is not None:
                cost.preemptions = slot.preemptions
                # slot-occupancy share: fraction of the decode grid's
                # capacity this request held over its residencies
                # (cumulative across preemption re-runs; None when it
                # retired without a decode chunk in between)
                cost.grid_steps += (self.stats.decode_steps
                                    - slot.steps0) * self.num_slots
                cost.slot_share = round(
                    cost.slot_steps / cost.grid_steps, 6) \
                    if cost.grid_steps > 0 else None
                _slo.record_request(cost.as_dict())
            _trace.instant("serving.retire", rid=slot.req.rid,
                           tokens=slot.gen,
                           preemptions=slot.preemptions,
                           tenant=getattr(slot.req, "tenant", "default"))
            _forensics.note_terminal(
                slot.req.rid, "completed", t=now,
                e2e_ms=(cost.e2e_ms if cost is not None
                        and cost.e2e_ms else None),
                ttft_ms=(cost.ttft_ms if cost is not None
                         and cost.ttft_ms else None),
                tenant=getattr(slot.req, "tenant", "default"),
                tokens=slot.gen, preemptions=slot.preemptions)

    def _preempt_victim_idx(self) -> Optional[int]:
        """Pick the eviction victim. Default: the YOUNGEST live request
        (highest slot index — the original recompute policy). With
        ``slo_preemption`` on: the request with the LOWEST eviction
        cost, ordered by (priority, prior preemptions, accumulated
        work) — evict the least important class first; within a class
        protect repeat victims (anti-starvation) and then evict the
        request that is cheapest to recompute. Work comes from the
        per-request cost record (prefill+decode tokens, cumulative
        across re-runs) when the monitor keeps one, else the current
        run's KV length — the monitor-off proxy of the same quantity."""
        if not self._slo_preemption:
            for idx in range(self.num_slots - 1, -1, -1):
                slot = self.slots[idx]
                if slot is not None and not slot.done:
                    return idx
            return None
        best_idx, best_key = None, None
        for idx in range(self.num_slots):
            slot = self.slots[idx]
            if slot is None or slot.done:
                continue
            work = slot.kv_len
            if slot.cost is not None:
                work = slot.cost.prefill_tokens + slot.cost.decode_tokens
            key = (getattr(slot.req, "priority", 0), slot.preemptions,
                   work, -idx)       # final tie-break: youngest
            if best_key is None or key < best_key:
                best_idx, best_key = idx, key
        return best_idx

    def _preempt_one(self) -> bool:
        """Evict one live request (recompute policy: pages freed,
        request requeued at the FRONT so it re-runs before newcomers);
        the victim is :meth:`_preempt_victim_idx`'s. False when
        nothing can be evicted."""
        idx = self._preempt_victim_idx()
        if idx is None:
            return False
        slot = self.slots[idx]
        self.slots[idx] = None
        self._state_dirty = self._bt_dirty = True
        now = time.perf_counter() if _monitor.enabled() else None
        cost = slot.cost if now is not None else None
        if cost is not None and slot.t_tick is not None:
            # final page-seconds tick for this run, read before
            # the free — an evicted request PAID for the pages
            # it held even though the work is recomputed
            cost.page_seconds += (
                self.cache.alloc.page_count(slot.req.rid)
                * (now - slot.t_tick))
        self.cache.alloc.free(slot.req.rid)
        slot.req._preempt_count = getattr(
            slot.req, "_preempt_count", 0) + 1
        self.queue.appendleft(slot.req)
        self.stats.preempted += 1
        # the evicted request's sampled-but-unretired tokens are
        # recomputed from scratch: move them to the discarded
        # column so generated - discarded stays == emitted
        self.stats.tokens_discarded += slot.gen
        _monitor.inc("serving.requests.preempted")
        _monitor.inc("serving.tokens.discarded", slot.gen,
                     doc="sampled tokens thrown away by "
                         "preemption recompute")
        if now is not None:
            # the re-queue refreshes t_enqueue: the NEXT wait
            # accumulates onto the record's cumulative
            # queue_wait_ms at re-admission (the histogram
            # observes each wait once, the record keeps the sum)
            slot.req._t_enqueue = now
            if cost is not None:
                cost.discarded_tokens += slot.gen
                cost.grid_steps += (self.stats.decode_steps
                                    - slot.steps0) \
                    * self.num_slots
            tenant = getattr(slot.req, "tenant", "default") \
                or "default"
            _trace.instant("serving.preempt", rid=slot.req.rid,
                           discarded=slot.gen, tenant=tenant)
            # the victim-selection inputs that chose this slot — the
            # _preempt_victim_idx key, recorded so the eviction is
            # auditable (forensics decision ring + the victim's own
            # timeline)
            work = slot.kv_len
            if cost is not None:
                work = (cost.prefill_tokens + cost.decode_tokens)
            policy = "slo" if self._slo_preemption else "youngest"
            victim = dict(policy=policy, slot=idx,
                          priority=getattr(slot.req, "priority", 0),
                          prior_preemptions=slot.preemptions,
                          work=int(work))
            _forensics.decision("preempt", rid=slot.req.rid,
                                discarded=slot.gen, **victim)
            _forensics.note(slot.req.rid, "preempt", t=now,
                            tenant=tenant, discarded=slot.gen,
                            **victim)
        return True

    def _defer(self, req: "Request", reason: str, **inputs):
        """Record one admission-scan deferral (forensics timeline +
        decision ring, both self-gated and coalescing — a head request
        blocked on the same reason for many steps is ONE record with a
        count, not a flood)."""
        _forensics.note_defer(req.rid, reason, **inputs)
        _forensics.decision("defer", rid=req.rid, reason=reason,
                            **inputs)

    def _admit(self):
        # PAIRED SCANS: this FIFO body and _admit_policy below share
        # the admission-control math (watermark, idle override,
        # alloc-failure enforce, group fill) by deliberate copy — the
        # flag-off path must stay byte-identical to the pre-policy
        # engine, so it is never routed through policy code. A fix to
        # the shared math MUST be applied to both.
        if self._priority_admission or self._tenant_cap:
            return self._admit_policy()
        while self.queue:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                self._defer(self.queue[0], "no_free_slot",
                            queue_depth=len(self.queue))
                break
            req = self.queue[0]
            plen = int(np.asarray(req.prompt).shape[0])
            s_pad = max(self._bucket(plen), self.page_size)
            need = s_pad // self.page_size
            idle = not any(s is not None and not s.done
                           for s in self.slots)
            if (self._free_slack() - need < self.watermark_pages
                    and not idle):        # head-of-line admission control
                self._defer(req, "watermark",
                            free_slack=self._free_slack(), need=need,
                            watermark_pages=self.watermark_pages,
                            queue_depth=len(self.queue))
                break
            self.queue.popleft()
            if self._alloc_for(req, s_pad) is None:
                self.queue.appendleft(req)
                self._defer(req, "alloc_failed", need=need,
                            free_pages=self.cache.alloc.free_pages,
                            queue_depth=len(self.queue))
                # an idle engine that cannot place its head request will
                # never make progress — that is a sizing error, not a
                # transient
                E.enforce(not idle,
                          f"request {req.rid} needs {need} pages but only "
                          f"{self.cache.alloc.free_pages} exist free on an "
                          f"idle engine", error=E.ResourceExhaustedError)
                break
            # group same-bucket waiters into this prefill dispatch (a
            # bounded look-through keeps overall FIFO fairness while
            # letting one program admit several requests). With the
            # prefix cache on, co-grouped requests must also match the
            # head's cached prefix length — the tail program's context
            # page count is a static compile-time constant per group.
            head_cached = getattr(req, "_pfx_cached", 0)
            group = [req]
            scanned = 0
            while (len(group) < len(free)
                   and scanned < len(self.queue)
                   and self._free_slack() - need
                   >= self.watermark_pages):
                cand = self.queue[scanned]
                cp = int(np.asarray(cand.prompt).shape[0])
                if max(self._bucket(cp), self.page_size) != s_pad or (
                        self._prefix is not None
                        and self._match_len(cand) != head_cached):
                    scanned += 1
                    continue
                if self._alloc_for(cand, s_pad) is None:
                    break
                if getattr(cand, "_pfx_cached", 0) != head_cached:
                    # an eviction inside _alloc_for shifted the match;
                    # not groupable this pass — leave it queued
                    self.cache.alloc.free(cand.rid)
                    scanned += 1
                    continue
                del self.queue[scanned]
                group.append(cand)
            self._prefill_group(free, group, s_pad)

    def _admit_policy(self):
        """Priority-class admission (``priority_admission`` /
        ``tenant_inflight_cap``): each pass admits the
        highest-priority eligible request — ties broken by queue
        position, i.e. arrival order, with preemption re-queues at the
        front — instead of the FIFO head, and a tenant already holding
        ``tenant_inflight_cap`` live slots is skipped (its requests
        wait without blocking other tenants' head-of-line). The cap
        WITHOUT priority admission keeps strict FIFO order among
        eligible requests — the cap alone must not change scheduling
        class semantics (the flag doc's contract). Same page
        watermark, idle override, and same-bucket grouping as the FIFO
        scan; grouping may co-admit lower-priority same-bucket waiters
        into slots of the dispatch that would otherwise idle — a
        bounded, one-dispatch-deep inversion traded for batched
        prefill. PAIRED with _admit's FIFO body (see the comment
        there): fixes to the shared admission-control math go in
        both."""
        cap = self._tenant_cap
        inflight: Dict[str, int] = {}
        if cap:
            for s in self.slots:
                if s is not None:
                    t = getattr(s.req, "tenant", "default")
                    inflight[t] = inflight.get(t, 0) + 1
        while self.queue:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                self._defer(self.queue[0], "no_free_slot",
                            queue_depth=len(self.queue))
                break
            pos = None
            for j, r in enumerate(self.queue):
                if cap and inflight.get(
                        getattr(r, "tenant", "default"), 0) >= cap:
                    continue
                if not self._priority_admission:
                    pos = j               # cap-only: first eligible (FIFO)
                    break
                if pos is None or getattr(r, "priority", 0) \
                        > getattr(self.queue[pos], "priority", 0):
                    pos = j
            if pos is None:
                # every waiter's tenant is at cap
                self._defer(self.queue[0], "tenant_cap", cap=cap,
                            queue_depth=len(self.queue))
                break
            req = self.queue[pos]
            plen = int(np.asarray(req.prompt).shape[0])
            s_pad = max(self._bucket(plen), self.page_size)
            need = s_pad // self.page_size
            idle = not any(s is not None and not s.done
                           for s in self.slots)
            if (self._free_slack() - need < self.watermark_pages
                    and not idle):
                self._defer(req, "watermark",
                            free_slack=self._free_slack(), need=need,
                            watermark_pages=self.watermark_pages,
                            queue_depth=len(self.queue))
                break
            del self.queue[pos]
            if self._alloc_for(req, s_pad) is None:
                self.queue.insert(pos, req)
                self._defer(req, "alloc_failed", need=need,
                            free_pages=self.cache.alloc.free_pages,
                            queue_depth=len(self.queue))
                E.enforce(not idle,
                          f"request {req.rid} needs {need} pages but only "
                          f"{self.cache.alloc.free_pages} exist free on an "
                          f"idle engine", error=E.ResourceExhaustedError)
                break
            head_cached = getattr(req, "_pfx_cached", 0)
            group = [req]
            if cap:
                t = getattr(req, "tenant", "default")
                inflight[t] = inflight.get(t, 0) + 1
            # group fill in PRIORITY order (ties: queue position), not
            # queue order — an equal-or-higher-priority same-bucket
            # waiter must not lose its seat in the dispatch to an
            # earlier-queued lower-priority one. Cap-only mode fills
            # in queue order (FIFO semantics preserved).
            if self._priority_admission:
                order = sorted(
                    range(len(self.queue)),
                    key=lambda j: (
                        -getattr(self.queue[j], "priority", 0), j))
            else:
                order = list(range(len(self.queue)))
            picked: List[int] = []
            for j in order:
                if len(group) >= len(free):
                    break
                if (self._free_slack() - need
                        < self.watermark_pages):
                    break
                cand = self.queue[j]
                cp = int(np.asarray(cand.prompt).shape[0])
                ct = getattr(cand, "tenant", "default")
                if max(self._bucket(cp), self.page_size) != s_pad or (
                        cap and inflight.get(ct, 0) >= cap) or (
                        self._prefix is not None
                        and self._match_len(cand) != head_cached):
                    continue
                if self._alloc_for(cand, s_pad) is None:
                    break
                if getattr(cand, "_pfx_cached", 0) != head_cached:
                    # eviction inside _alloc_for shifted the match;
                    # not groupable this pass — leave it queued
                    self.cache.alloc.free(cand.rid)
                    continue
                picked.append(j)
                group.append(cand)
                if cap:
                    inflight[ct] = inflight.get(ct, 0) + 1
            for j in sorted(picked, reverse=True):
                del self.queue[j]
            self._prefill_group(free, group, s_pad)

    def _prefill_group(self, free: List[int], group: List["Request"],
                       s_pad: int):
        """One batched prefill for same-bucket requests, padded to a
        power-of-two group size (bounds compiles at log2(slots) per
        bucket); dummy rows carry all-sentinel page tables and never
        touch the pool."""
        need = s_pad // self.page_size
        mon = _monitor.enabled()
        t_admit = None
        if mon:
            t_admit = time.perf_counter()
            _forensics.decision(
                "admit", rid=group[0].rid, group=len(group),
                bucket=s_pad, free_slots=len(free),
                queue_depth=len(self.queue),
                pfx_cached=int(getattr(group[0], "_pfx_cached", 0)))
            for r in group:
                wait_ms = None
                t_enq = getattr(r, "_t_enqueue", None)
                if t_enq is not None:
                    wait_ms = (t_admit - t_enq) * 1e3
                    _observe_latency(
                        "serving.latency.queue_wait_ms", wait_ms,
                        "enqueue (or preemption re-queue) to admission")
                    cost = getattr(r, "_cost", None)
                    if cost is not None:
                        # CUMULATIVE across preemption re-queues: the
                        # histogram above observes each wait once; the
                        # record answers "how long did this request
                        # spend queued in total"
                        cost.queue_wait_ms += wait_ms
                _trace.instant("serving.admit", rid=r.rid)
                # the admit event carries the prefix-cache match result
                # (cached prefix length this group was grouped on)
                _forensics.note(
                    r.rid, "admit", t=t_admit, bucket=s_pad,
                    group=len(group),
                    wait_ms=round(wait_ms, 3)
                    if wait_ms is not None else None,
                    pfx_cached=int(getattr(r, "_pfx_cached", 0)))
        g = 1
        while g < len(group):
            g *= 2
        # with the prefix cache on, every member of this group shares
        # the same cached page-aligned prefix length (admission grouped
        # by it): the program prefills only the uncached tail, reading
        # the shared context pages without ever writing them
        cached = int(getattr(group[0], "_pfx_cached", 0)) \
            if self._prefix is not None else 0
        ncp = cached // self.page_size
        s_eff = s_pad - cached
        need_eff = need - ncp
        ids = np.zeros((g, s_eff), np.int32)
        rows = np.full((g, need_eff), self.cache.num_pages, np.int32)
        ctx_rows = np.full((g, ncp), self.cache.num_pages, np.int32)
        slen = np.ones(g, np.int32)
        temps = np.zeros(g, np.float32)
        keys = np.zeros((g, 2), np.uint32)
        slots = []
        for j, r in enumerate(group):
            plen = int(np.asarray(r.prompt).shape[0])
            ids[j, :plen - cached] = np.asarray(r.prompt,
                                                np.int32)[cached:]
            brow = self.cache.alloc.block_row(r.rid, need)
            ctx_rows[j] = brow[:ncp]
            rows[j] = brow[ncp:]
            slen[j] = plen - cached
            temps[j] = r.temperature
            slot = _Slot(r, self._keys_for(r))
            slot.kv_len = plen
            slot.preemptions = getattr(r, "_preempt_count", 0)
            keys[j] = slot.keys[0]
            slots.append(slot)
        sampled = any(r.temperature > 0 for r in group)
        pf = self._prefill_shared_fn(g, s_eff, ncp, sampled) if cached \
            else self._prefill_fn(g, s_pad, sampled)
        pf_args = (self.params, jnp.asarray(ids), self.cache.pool["k"],
                   self.cache.pool["v"])
        pf_kwargs = dict(page_rows=jnp.asarray(rows),
                         slen=jnp.asarray(slen), temp=jnp.asarray(temps),
                         key=jnp.asarray(keys))
        if cached:
            pf_kwargs["ctx_rows"] = jnp.asarray(ctx_rows)
        exec_rec = None
        pf_flops_share = None
        if mon:
            # introspection-registry record, BEFORE the dispatch that
            # donates the pool buffers (once per specialization)
            key = self._record_serving_program(
                ("serving.prefill_shared", g, s_eff, ncp, sampled)
                if cached else ("serving.prefill", g, s_pad, sampled),
                f"serving.prefill_shared[g{g},s{s_eff},ctx{ncp}]"
                if cached else f"serving.prefill[g{g},s{s_pad}]",
                pf, pf_args, pf_kwargs, donated=(2, 3))
            from ..monitor import exectime as _exectime
            exec_rec = _exectime.maybe_sample(key, feed_last=False)
            # modeled-FLOPs attribution: the registered program's
            # cost-analysis count split across the real requests that
            # shared this dispatch (dummy pad rows attribute nowhere)
            pf_flops = self._program_flops(key)
            if pf_flops:
                pf_flops_share = pf_flops / len(group)
        with _trace.span("serving.prefill", group=len(group),
                         s_pad=s_pad), \
                _pcap.annotate("serving.prefill"):
            pk, pv, tok_a = pf(*pf_args, **pf_kwargs)
            self.cache.pool = {"k": pk, "v": pv}
            # the np.asarray download syncs the device — the span ends
            # (and TTFT is stamped) when the first token actually EXISTS
            # on the host, not when the dispatch returned
            toks = np.asarray(tok_a)
        if exec_rec is not None:
            # the download above already synchronized: rec(None) adds
            # ZERO extra block_until_ready calls at this seam
            exec_rec(None)
        t_first = None
        if mon:
            # TTFT is NOT observed here: a preemption would discard
            # this run's tokens and re-prefill, double-sampling the
            # histogram with a first token the client never saw. The
            # slot carries t_first to _retire, which observes once per
            # completed request. The lifecycle instant still marks
            # every prefill (preempted runs included) in the trace.
            t_first = time.perf_counter()
            for r in group:
                _trace.instant("serving.first_token", rid=r.rid)
                # pure host bookkeeping AFTER the np.asarray download
                # above already synchronized: zero added device syncs
                _forensics.note(r.rid, "first_token", t=t_first)
        for j, (r, slot) in enumerate(zip(group, slots)):
            self.cache.alloc.advance(r.rid, int(slen[j]) + cached)
            tok = int(toks[j])
            slot.tokens.append(tok)
            slot.pending = tok
            slot.gen = 1
            slot.t_first = slot.t_last = t_first
            if mon:
                slot.cost = getattr(r, "_cost", None)
                # page-seconds integrate from admission (pages were
                # allocated in _admit) at chunk-edge resolution
                slot.t_tick = t_admit
                slot.steps0 = self.stats.decode_steps
                if slot.cost is not None:
                    slot.cost.prefill_tokens += int(slen[j])
                    if cached:
                        slot.cost.prefix_cached_tokens += cached
                        if pf_flops_share:
                            # modeled: the tail program's per-padded-
                            # token cost scaled by the tokens the cache
                            # served — what a full prefill would have
                            # added, to first order
                            slot.cost.prefill_flops_saved += (
                                pf_flops_share / s_eff * cached)
                    if pf_flops_share:
                        slot.cost.model_flops += pf_flops_share
            slot.done = (tok == r.eos_token_id
                         if r.eos_token_id is not None else False) \
                or slot.gen >= r.max_new_tokens
            self.slots[free[j]] = slot
            self.stats.admitted += 1
            self.stats.tokens_generated += 1
            self.stats.tokens_prefilled += int(slen[j])
            _monitor.inc("serving.requests.admitted")
            # the prefill-sampled first token counts here so the counter
            # agrees with stats.tokens_generated
            _monitor.inc("serving.tokens.generated")
            _monitor.inc("serving.tokens.prefilled", int(slen[j]))
        self._state_dirty = self._bt_dirty = True

    def _pick_chunk(self, live_idx: List[int]) -> int:
        """Turbo chunk when no retire/join/EOS could land mid-chunk:
        the slot grid is full, everyone's remaining run covers it, and
        nobody can stop early on EOS. Occupancy is then provably
        unaffected, and per-chunk overhead amortises 4x further."""
        if len(live_idx) < self.num_slots:
            return self.decode_chunk
        for i in live_idx:
            s = self.slots[i]
            if (s.req.eos_token_id is not None
                    or s.req.max_new_tokens - s.gen < self.turbo_chunk):
                return self.decode_chunk
        return self.turbo_chunk

    def _ensure_chunk_capacity(self, live_idx: List[int],
                               chunk: int) -> List[int]:
        """Reserve pages for up to ``chunk`` appends per live slot,
        preempting the youngest requests on OOM. Returns the (possibly
        shrunk) live index list."""
        i = 0
        while i < len(live_idx):
            idx = live_idx[i]
            slot = self.slots[idx]
            if slot is None:              # preempted by an earlier pass
                live_idx.pop(i)
                continue
            appends = min(chunk,
                          slot.req.max_new_tokens - slot.gen + 1)
            got = self.cache.alloc.ensure(slot.req.rid,
                                          slot.kv_len + appends)
            if got is None:
                # reclaim cold prefix-cache pages before sacrificing a
                # live request (flag off: a no-op 0, byte-identical)
                if self._evict_pages(1) == 0:
                    E.enforce(self._preempt_one(),
                              "page pool exhausted with nothing left to "
                              "preempt", error=E.ResourceExhaustedError)
                continue                  # retry this slot
            if got[0] or got[1]:
                self._bt_dirty = True
            self.cache.apply_cow(got[1])
            i += 1
        return [idx for idx in live_idx if self.slots[idx] is not None]

    def step(self) -> bool:
        """One scheduling iteration: expire (when any request carries a
        deadline) -> retire -> compact -> admit -> one decode chunk.
        Returns False when the engine is fully idle."""
        if self._deadlines_seen:
            self._expire_due()
        for idx in range(self.num_slots):
            if self.slots[idx] is not None and self.slots[idx].done:
                self._retire(idx)
        self._compact()
        self._admit()
        _monitor.set_gauge("serving.queue.depth", len(self.queue),
                           doc="requests waiting for admission")
        in_use = self.cache.alloc.used_pages
        self.stats.peak_pages_in_use = max(self.stats.peak_pages_in_use,
                                           in_use)
        _monitor.set_gauge("serving.pages.in_use", in_use,
                           doc="KV pages currently allocated")

        live_idx = [i for i, s in enumerate(self.slots)
                    if s is not None and not s.done]
        if _monitor.enabled():
            # autoscale feed (monitor/slo.py): one host tick per
            # scheduling step — queue depth, live slots, page slack.
            # The gauges themselves are recomputed at scrape time.
            _slo.note_sched_tick(
                len(self.queue), len(live_idx), self.num_slots,
                self.cache.alloc.free_pages / self.cache.num_pages
                if self.cache.num_pages else 0.0)
        if self._frame_pub is not None:
            # federation frame on the same host tick (rate-limited
            # inside; pure host state — zero device syncs)
            self._frame_pub.maybe_publish(self)
        if not live_idx:
            return bool(self.queue) or any(
                s is not None for s in self.slots)
        C = self._pick_chunk(live_idx)
        live_idx = self._ensure_chunk_capacity(live_idx, C)
        if not live_idx:
            return True
        if (self._spec_decode and C == self.turbo_chunk
                and not any(self.slots[i].req.temperature > 0
                            for i in live_idx)):
            # greedy turbo chunk: verify a self-drafted window in ONE
            # model pass instead of C sequential decode steps. The
            # turbo preconditions (full grid, no EOS, remaining run
            # covers the chunk) already hold, so accept/reject lands at
            # the same chunk boundary the sequential path downloads at.
            return self._spec_step(live_idx, C)

        B = self.num_slots
        if self._state_dirty:
            # (re)build the device-side slot state. The steady state —
            # chunk after chunk with no join/retire/new-page — reuses the
            # PREVIOUS chunk's returned device arrays untouched: the
            # scheduler's host work then stays off the per-token path.
            tokens = np.zeros(B, np.int32)
            kv_len = np.zeros(B, np.int32)
            done = np.ones(B, bool)
            gen = np.zeros(B, np.int32)
            temps = np.zeros(B, np.float32)
            max_new = np.zeros(B, np.int32)
            eos = np.full(B, -1, np.int32)
            for i in live_idx:
                s = self.slots[i]
                tokens[i], kv_len[i], done[i] = s.pending, s.kv_len, False
                gen[i], temps[i] = s.gen, s.req.temperature
                max_new[i] = s.req.max_new_tokens
                if s.req.eos_token_id is not None:
                    eos[i] = s.req.eos_token_id
            self._dev.update(
                tokens=jnp.asarray(tokens), kv_len=jnp.asarray(kv_len),
                done=jnp.asarray(done), gen=jnp.asarray(gen),
                temps=jnp.asarray(temps), max_new=jnp.asarray(max_new),
                eos=jnp.asarray(eos))
            self._sampled = any(self.slots[i].req.temperature > 0
                                for i in live_idx)
            self._state_dirty = False
        if self._bt_dirty:
            seq_ids = [self.slots[i].req.rid
                       if i in set(live_idx) else None for i in range(B)]
            self._dev["bt"] = jnp.asarray(self.cache.block_tables(seq_ids))
            self._bt_dirty = False
        if self._sampled:
            keys = np.zeros((C, B, 2), np.uint32)
            for i in live_idx:
                s = self.slots[i]
                for t in range(C):
                    keys[t, i] = s.keys[min(s.gen + t, len(s.keys) - 1)]
            keys = jnp.asarray(keys)
        else:
            keys = self._zero_keys[C]  # greedy: keys are never read

        d = self._dev
        ck = self._chunk_fns[(C, self._sampled)]
        ck_args = (self.params, self.cache.pool["k"],
                   self.cache.pool["v"], d["bt"], d["tokens"],
                   d["kv_len"], d["done"], d["gen"], keys, d["temps"],
                   d["max_new"], d["eos"])
        exec_rec = None
        ck_flops_share = None
        if _monitor.enabled():
            key = self._record_serving_program(
                ("serving.decode_chunk", C, self._sampled),
                f"serving.decode_chunk[c{C}"
                f"{',sampled' if self._sampled else ''}]",
                ck, ck_args, None, donated=(1, 2))
            from ..monitor import exectime as _exectime
            exec_rec = _exectime.maybe_sample(key, feed_last=False)
            # modeled-FLOPs attribution: the chunk program's registered
            # cost-analysis count split across the live slots sharing
            # this dispatch (done/empty slots ride along for free in
            # the static grid; the work exists because of the live
            # ones). None/0 when the backend never reported — skipped,
            # not fabricated.
            ck_flops = self._program_flops(key)
            if ck_flops:
                ck_flops_share = ck_flops / len(live_idx)
        with _trace.span("serving.decode_chunk", chunk=C,
                         live=len(live_idx)), \
                _pcap.annotate_step("serving.decode_chunk",
                                    self.stats.decode_steps):
            pk, pv, tok, kvl, done_a, gen_a, emitted = ck(*ck_args)
            self.cache.pool = {"k": pk, "v": pv}
            self._dev.update(tokens=tok, kv_len=kvl, done=done_a,
                             gen=gen_a)
            # ONE device->host transfer per chunk: every host-side fact
            # is derivable from the emitted grid (-1 = slot was done at
            # that step; a write and a sample happen exactly on non -1
            # steps). The download syncs, so the span's end — and the
            # t_chunk stamp below — is when the tokens reached the host.
            emitted = np.asarray(emitted)                # [C, B]
        if exec_rec is not None:
            # the emitted-grid download already synchronized this
            # chunk: rec(None) adds zero block_until_ready calls
            exec_rec(None)
        if _monitor.enabled():
            self._maybe_sample_kv_absmax()
        t_chunk = time.perf_counter() if _monitor.enabled() else None
        new_tokens = 0
        for i in live_idx:
            s = self.slots[i]
            toks = emitted[:, i]
            toks = toks[toks >= 0].tolist()
            if toks:
                s.tokens.extend(toks)
                new_tokens += len(toks)
                self.cache.alloc.advance(s.req.rid, len(toks))
                s.kv_len += len(toks)
                s.gen += len(toks)
                s.pending = toks[-1]
                s.t_last = t_chunk if t_chunk is not None else s.t_last
            if t_chunk is not None and s.cost is not None:
                # cost attribution at the chunk edge the emitted-grid
                # download above already synchronized: pure host reads
                # (allocator page counts, the cached program FLOPs) —
                # zero added device synchronizations at any rate
                if s.t_tick is not None:
                    s.cost.page_seconds += (
                        self.cache.alloc.page_count(s.req.rid)
                        * (t_chunk - s.t_tick))
                s.t_tick = t_chunk
                s.cost.slot_steps += C
                s.cost.decode_tokens += len(toks)
                if ck_flops_share:
                    s.cost.model_flops += ck_flops_share
            s.done = s.gen >= s.req.max_new_tokens or (
                s.req.eos_token_id is not None and bool(toks)
                and toks[-1] == s.req.eos_token_id)
        self.stats.decode_steps += C
        self.stats.tokens_generated += new_tokens
        self.stats.tokens_decoded += new_tokens
        self.stats._occ_steps += C * self.num_slots
        occ = self.stats.occupancy()
        _monitor.set_gauge("serving.batch.occupancy", round(occ, 4),
                           doc="generated tokens / (decode steps x slots)")
        _monitor.inc("serving.tokens.generated", new_tokens)
        return True

    def _draft_for(self, s: "_Slot", C: int) -> np.ndarray:
        """Draft a C-token verify window for one sequence: position 0
        is the real pending token (its KV is the one unwritten commit),
        positions 1..C-1 come from a bigram table folded incrementally
        over the request's own context (prompt + emitted tokens), with
        repeat-last as the cold-miss fallback. Pure host work — the
        table is a dict on the slot, extended only over tokens appended
        since the last draft."""
        if s.ng is None:
            s.ng = {}
        prompt = np.asarray(s.req.prompt)
        plen = int(prompt.shape[0])
        total = plen + len(s.tokens)

        def at(p):
            return int(prompt[p]) if p < plen else int(s.tokens[p - plen])

        for p in range(max(s.ng_n, 2), total):
            s.ng[(at(p - 2), at(p - 1))] = at(p)
        s.ng_n = total
        out = np.empty(C, np.int32)
        out[0] = s.pending
        p2, p1 = at(total - 2), at(total - 1)
        for t in range(1, C):
            nxt = s.ng.get((p2, p1), p1)
            out[t] = nxt
            p2, p1 = p1, nxt
        return out

    def _spec_step(self, live_idx: List[int], C: int) -> bool:
        """One speculative verify round over the greedy turbo chunk:
        write all C drafted positions' KV, run ONE attention pass over
        the window, and accept the longest run where the model's greedy
        prediction confirms the next draft. Token-identity with the
        sequential path is by construction: draft position 0 is the
        real pending token, so prediction 0 is exactly the sequential
        path's next token; each further draft is only kept when it
        EQUALS the greedy prediction before it, and the first emitted
        token after any rejection is again the model's own prediction.
        (Identity is at the math level: the verify window is a
        differently-shaped program than the turbo chunk, so in reduced
        precision an argmax near-tie can flip — exact in f32.)
        Rejected positions' KV stays in the pool as garbage masked out
        by sequence length and overwritten by later commits."""
        B = self.num_slots
        if self._bt_dirty:
            seq_ids = [self.slots[i].req.rid
                       if i in set(live_idx) else None for i in range(B)]
            self._dev["bt"] = jnp.asarray(self.cache.block_tables(seq_ids))
            self._bt_dirty = False
        drafts = np.zeros((B, C), np.int32)
        kv_len = np.zeros(B, np.int32)
        live_m = np.zeros(B, bool)
        for i in live_idx:
            s = self.slots[i]
            drafts[i] = self._draft_for(s, C)
            kv_len[i] = s.kv_len
            live_m[i] = True
        vf = self._spec_fn(C)
        vf_args = (self.params, self.cache.pool["k"],
                   self.cache.pool["v"], self._dev["bt"],
                   jnp.asarray(drafts), jnp.asarray(kv_len),
                   jnp.asarray(live_m))
        exec_rec = None
        vf_flops_share = None
        if _monitor.enabled():
            key = self._record_serving_program(
                ("serving.spec_chunk", C),
                f"serving.spec_chunk[c{C}]", vf, vf_args, None,
                donated=(1, 2))
            from ..monitor import exectime as _exectime
            exec_rec = _exectime.maybe_sample(key, feed_last=False)
            vf_flops = self._program_flops(key)
            if vf_flops:
                vf_flops_share = vf_flops / len(live_idx)
        with _trace.span("serving.spec_chunk", chunk=C,
                         live=len(live_idx)), \
                _pcap.annotate_step("serving.spec_chunk",
                                    self.stats.decode_steps):
            pk, pv, preds_a = vf(*vf_args)
            self.cache.pool = {"k": pk, "v": pv}
            preds = np.asarray(preds_a)                  # [B, C]
        if exec_rec is not None:
            exec_rec(None)
        if _monitor.enabled():
            self._maybe_sample_kv_absmax()
        t_chunk = time.perf_counter() if _monitor.enabled() else None
        new_tokens = 0
        accepted_total = 0
        for i in live_idx:
            s = self.slots[i]
            dr = drafts[i]
            col = preds[i]
            a = 0
            while a < C - 1 and dr[a + 1] == col[a]:
                a += 1
            emitted = [int(t) for t in col[:a + 1]]
            s.tokens.extend(emitted)
            new_tokens += len(emitted)
            accepted_total += a
            self.cache.alloc.advance(s.req.rid, len(emitted))
            s.kv_len += len(emitted)
            s.gen += len(emitted)
            s.pending = emitted[-1]
            s.t_last = t_chunk if t_chunk is not None else s.t_last
            if t_chunk is not None and s.cost is not None:
                if s.t_tick is not None:
                    s.cost.page_seconds += (
                        self.cache.alloc.page_count(s.req.rid)
                        * (t_chunk - s.t_tick))
                s.t_tick = t_chunk
                s.cost.slot_steps += C
                s.cost.decode_tokens += len(emitted)
                if vf_flops_share:
                    s.cost.model_flops += vf_flops_share
            if t_chunk is not None:
                # aggregate fold, no event append: spec rounds are
                # per-chunk-rate and would flood the bounded timeline
                _forensics.note_spec(s.req.rid, C - 1, a)
            # turbo preconditions rule out EOS; only the length bound
            # can finish a sequence here
            s.done = s.gen >= s.req.max_new_tokens
        self.stats.decode_steps += C
        self.stats.tokens_generated += new_tokens
        self.stats.tokens_decoded += new_tokens
        self.stats._occ_steps += C * self.num_slots
        self.stats.spec_rounds += len(live_idx)
        self.stats.spec_drafted += (C - 1) * len(live_idx)
        self.stats.spec_accepted += accepted_total
        occ = self.stats.occupancy()
        _monitor.set_gauge("serving.batch.occupancy", round(occ, 4),
                           doc="generated tokens / (decode steps x slots)")
        _monitor.inc("serving.tokens.generated", new_tokens)
        _monitor.inc("serving.spec.rounds", len(live_idx),
                     doc="per-sequence speculative verify rounds")
        _monitor.inc("serving.spec.drafted", (C - 1) * len(live_idx),
                     doc="n-gram draft tokens proposed for verification")
        _monitor.inc("serving.spec.accepted", accepted_total,
                     doc="draft tokens confirmed by the greedy verify")
        # the device-side sequential slot state is stale after a spec
        # round (tokens/kv_len/gen advanced on the host): rebuild it
        # before the next sequential chunk
        self._state_dirty = True
        return True

    def _maybe_sample_kv_absmax(self):
        """KV-page absmax distribution feed (numerics plane): every
        1-in-N chunks (``PADDLE_TPU_KV_SAMPLE``; 0 disables) compute
        per-layer per-page max|K| / max|V| over the pool, keep only
        the pages the allocator holds live (free pages are zeros that
        would drown the distribution), and record them. Runs right
        after the chunk's token download — the device is idle, so the
        small [L, P] compute + transfer rides the existing seam with
        zero extra synchronizations of in-flight work."""
        from ..monitor import numerics as _numerics
        rate = _numerics.kv_sample_rate()
        if rate <= 0:
            return
        self._kv_chunks += 1
        if self._kv_chunks < rate:
            return
        self._kv_chunks = 0
        in_use = np.flatnonzero(self.cache.alloc._ref > 0)
        if in_use.size == 0:
            return
        if self._kv_absmax_fn is None:
            if self._kv_quant:
                # quantized pool: codes [L, P, kv, page, hd] + scales
                # [L, P, kv]. absmax = max|code|·scale; also surface the
                # quantizer's own health — the scale magnitudes and the
                # fraction of codes pinned at the clip rail (±127)
                def _q_absmax(k, v):
                    def one(leaf):
                        am = jnp.max(jnp.abs(leaf["q"]), axis=(3, 4))
                        return jnp.max(am.astype(jnp.float32)
                                       * leaf["s"], axis=2)
                    clip = (
                        jnp.mean((jnp.abs(k["q"]) == 127),
                                 axis=(0, 2, 3, 4)).astype(jnp.float32)
                        + jnp.mean((jnp.abs(v["q"]) == 127),
                                   axis=(0, 2, 3, 4)).astype(jnp.float32)
                    ) * 0.5                               # [P]
                    scales = jnp.maximum(jnp.max(k["s"], axis=2),
                                         jnp.max(v["s"], axis=2))
                    return one(k), one(v), scales, clip
                self._kv_absmax_fn = jax.jit(_q_absmax)
            else:
                # pool layout [L, P, kv, page, hd] -> per-layer per-page
                self._kv_absmax_fn = jax.jit(
                    lambda k, v: (
                        jnp.max(jnp.abs(k), axis=(2, 3, 4)
                                ).astype(jnp.float32),
                        jnp.max(jnp.abs(v), axis=(2, 3, 4)
                                ).astype(jnp.float32)))
        out = self._kv_absmax_fn(self.cache.pool["k"],
                                 self.cache.pool["v"])
        km = np.asarray(out[0])[:, in_use]
        vm = np.asarray(out[1])[:, in_use]
        _numerics.record_kv_absmax(km, vm)
        if self._kv_quant:
            scales = np.asarray(out[2])[:, in_use]
            clip = float(np.mean(np.asarray(out[3])[in_use]))
            _numerics.record_kv_quant(scales, clip)

    def run(self, requests=None, max_steps: int = 1_000_000
            ) -> Dict[int, RequestOutput]:
        """Drive the scheduler until every submitted request completes;
        returns {rid: RequestOutput}."""
        if requests:
            for r in requests:
                self.submit(r)
        steps = 0
        while self.step():
            steps += 1
            E.enforce(steps < max_steps,
                      f"engine did not drain within {max_steps} steps")
        return self.outputs
