"""Paged KV cache: page-pool tensors + block-table allocator + the
paged prefill/decode data plane.

Reference capability: vLLM's PagedAttention block manager (the
dominant serving-stack design: KV lives in fixed-size pages named by
per-sequence block tables, so HBM is allocated at page granularity
instead of max-length ring buffers) realised TPU-native per Ragged
Paged Attention (arxiv 2604.15464, PAPERS.md).

Three layers:

- ``PageAllocator`` — the host-side control plane: a free list plus
  ref-counted pages per sequence (alloc / ensure(+copy-on-write) /
  advance / fork / free). Pure Python+numpy; never touches the device.
- ``PagedKVCache`` — the pool tensors (one page grid per layer) married
  to an allocator; owns layout and the block-table/length device views.
- ``paged_prefill`` / ``paged_decode_step`` — pure-jax data plane with
  the same (params, ..., config) shape as the ring-buffer
  ``(init_cache, prefill, decode_step)`` contract in models/llama.py,
  but generic over the model family: any module exposing the decoder
  seam (``_qkv_proj``-compatible layers, ``decode_mlp``, ``_head``)
  plugs in — llama and the MoE families both do.

Pool layout: ``[L, num_pages, kv_heads, page_size, head_dim]``. The
ISSUE/vLLM order puts page_size before kv_heads; the kv-head axis is
hoisted OUTSIDE the page axis here so the decode kernel's per-page
block ``(1, 1, page_size, head_dim)`` satisfies Mosaic's last-two-dims
tiling rule for every page size (see kernels/paged_attention.py).

Writes into pages use scatter-with-drop: block-table entries equal to
``num_pages`` are an explicit "no page" sentinel, so a padded prompt
page or an inactive decode slot drops its write instead of corrupting
page 0 — the allocator owns the sentinel discipline.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import enforce as E
from ..models.llama import _head_logits, _mm, _qkv_proj, _rms
from ..nn.functional.attention import rope_raw, rope_tables

__all__ = ["PageAllocator", "PagedKVCache", "PrefixCache", "init_pool",
           "paged_prefill", "paged_prefill_shared", "paged_decode_step",
           "paged_verify_window"]


# ---------------------------------------------------------------------------
# host-side control plane
# ---------------------------------------------------------------------------

class PageAllocator:
    """Free-list page allocator with per-sequence block tables and
    ref-counted pages (copy-on-fork for beam/top-k style sequence
    sharing). All methods are host-side and O(pages touched); OOM is a
    ``None`` return with state unchanged — admission control, not an
    exception."""

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_seq: int):
        E.enforce(num_pages >= 1, f"num_pages must be >= 1, got {num_pages}")
        E.enforce(page_size >= 1, f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._ref = np.zeros(num_pages, np.int32)
        # prefix-cache pins: each held page carries exactly one extra
        # ref owned by the radix cache (0/1 per page), so
        # seq-held-counts + cache-holds == _ref stays auditable
        self._cache_hold = np.zeros(num_pages, np.int32)
        # seq_id -> {"pages": [page ids], "len": tokens written}
        self._seqs: Dict[int, dict] = {}

    # -- introspection ------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_size)

    def seq_len(self, seq_id: int) -> int:
        return self._seqs[seq_id]["len"]

    def seq_pages(self, seq_id: int) -> List[int]:
        return list(self._seqs[seq_id]["pages"])

    def page_count(self, seq_id: int) -> int:
        """Pages currently held by this sequence (no list copy — the
        engine's per-chunk cost attribution reads it per live slot)."""
        return len(self._seqs[seq_id]["pages"])

    def block_row(self, seq_id: int, width: Optional[int] = None
                  ) -> np.ndarray:
        """This sequence's block-table row, padded with the ``num_pages``
        sentinel (the no-page value the scatter path drops)."""
        width = self.max_pages_per_seq if width is None else width
        row = np.full(width, self.num_pages, np.int32)
        pages = self._seqs[seq_id]["pages"]
        row[:len(pages)] = pages
        return row

    def check_invariants(self):
        """Refcount bookkeeping audit (tests): every page is either free
        (ref 0) or referenced exactly as many times as sequences AND the
        prefix cache hold it, and the free list is duplicate-free. The
        cache-hold half is what proves prefix-cache eviction can never
        free a page a live sequence holds: ``cache_release`` only
        returns a page to the free list when dropping the cache's own
        ref leaves zero — a live holder keeps it referenced."""
        counts = np.zeros(self.num_pages, np.int32)
        for s in self._seqs.values():
            for p in s["pages"]:
                counts[p] += 1
        if not np.array_equal(counts + self._cache_hold, self._ref):
            raise AssertionError(
                f"refcount drift: held={counts.tolist()} "
                f"cached={self._cache_hold.tolist()} "
                f"ref={self._ref.tolist()}")
        if np.any(self._cache_hold < 0) or np.any(self._cache_hold > 1):
            raise AssertionError(
                f"cache-hold out of range: {self._cache_hold.tolist()}")
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages on the free list")
        if any(self._ref[p] != 0 for p in free):
            raise AssertionError("referenced page on the free list")
        if len(free) + int((self._ref > 0).sum()) != self.num_pages:
            raise AssertionError("leaked page: neither free nor referenced")

    # -- lifecycle ----------------------------------------------------------

    def _take(self, n: int) -> Optional[List[int]]:
        if len(self._free) < n:
            return None
        taken = [self._free.pop() for _ in range(n)]
        for p in taken:
            self._ref[p] += 1
        return taken

    def alloc(self, seq_id: int, n_tokens: int) -> Optional[List[int]]:
        """Create a sequence with capacity for ``n_tokens`` (its written
        length starts at 0 — ``advance`` after the KV lands). None = OOM."""
        E.enforce(seq_id not in self._seqs,
                  f"sequence {seq_id} already allocated")
        need = self.pages_for(n_tokens)
        E.enforce(need <= self.max_pages_per_seq,
                  f"{n_tokens} tokens need {need} pages > "
                  f"max_pages_per_seq {self.max_pages_per_seq}")
        pages = self._take(need)
        if pages is None:
            return None
        self._seqs[seq_id] = {"pages": pages, "len": 0}
        return pages

    def alloc_prefix(self, seq_id: int, shared_pages: List[int],
                     n_tokens: int) -> Optional[List[int]]:
        """Create a sequence whose leading pages are SHARED (pure
        refcount bumps — the ``fork`` seam at admission granularity):
        ``shared_pages`` hold the committed KV of a cached prompt
        prefix; the remainder up to ``n_tokens`` capacity is taken
        fresh. The shared region is strictly shorter than the prompt
        (the cache caps matches below the last prompt token), so the
        holder's writes start at/after ``len(shared_pages)`` pages and
        a shared page is never written — CoW via ``ensure`` still
        covers any later aliasing. None = OOM, state unchanged."""
        E.enforce(seq_id not in self._seqs,
                  f"sequence {seq_id} already allocated")
        need = self.pages_for(n_tokens)
        E.enforce(need <= self.max_pages_per_seq,
                  f"{n_tokens} tokens need {need} pages > "
                  f"max_pages_per_seq {self.max_pages_per_seq}")
        E.enforce(len(shared_pages) < need,
                  f"shared prefix ({len(shared_pages)} pages) must "
                  f"leave a fresh tail page (need {need})")
        E.enforce(all(self._ref[p] > 0 for p in shared_pages),
                  "shared prefix references an unreferenced page")
        fresh = self._take(need - len(shared_pages))
        if fresh is None:
            return None
        for p in shared_pages:
            self._ref[p] += 1
        pages = list(shared_pages) + fresh
        self._seqs[seq_id] = {"pages": pages, "len": 0}
        return pages

    def cache_hold(self, page: int):
        """Pin ``page`` with the prefix cache's own ref. Only committed
        (currently referenced) pages may be cached — insertion runs at
        retirement BEFORE the sequence's ``free``."""
        E.enforce(self._ref[page] > 0,
                  f"cache_hold on unreferenced page {page}")
        E.enforce(self._cache_hold[page] == 0,
                  f"page {page} already cache-held")
        self._ref[page] += 1
        self._cache_hold[page] = 1

    def cache_release(self, page: int) -> int:
        """Drop the cache's pin on ``page``. Returns 1 if the page hit
        the free list (no live sequence held it), else 0 — eviction by
        construction never frees a live sequence's page."""
        E.enforce(self._cache_hold[page] == 1,
                  f"cache_release on unheld page {page}")
        self._cache_hold[page] = 0
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)
            return 1
        return 0

    def ensure(self, seq_id: int, total_tokens: int
               ) -> Optional[Tuple[List[int], List[Tuple[int, int]]]]:
        """Grow capacity to ``total_tokens`` and copy-on-write any SHARED
        page the upcoming writes (positions >= current len) would touch.
        Returns (new_pages, cow_pairs[(src, dst)]) — the caller must
        mirror cow_pairs onto the device pool — or None on OOM (state
        unchanged)."""
        s = self._seqs[seq_id]
        need_total = self.pages_for(total_tokens)
        E.enforce(need_total <= self.max_pages_per_seq,
                  f"{total_tokens} tokens need {need_total} pages > "
                  f"max_pages_per_seq {self.max_pages_per_seq}")
        grow = max(0, need_total - len(s["pages"]))
        first_written = s["len"] // self.page_size
        cow_idx = [i for i in range(first_written,
                                    min(len(s["pages"]), need_total))
                   if self._ref[s["pages"][i]] > 1]
        fresh = self._take(grow + len(cow_idx))
        if fresh is None:
            return None
        new_pages, cow_dst = fresh[:grow], fresh[grow:]
        cow_pairs = []
        for i, dst in zip(cow_idx, cow_dst):
            src = s["pages"][i]
            cow_pairs.append((src, dst))
            self._ref[src] -= 1          # shared: never hits 0 here
            s["pages"][i] = dst
        s["pages"].extend(new_pages)
        return new_pages, cow_pairs

    def advance(self, seq_id: int, n_tokens: int = 1):
        """Record ``n_tokens`` written; capacity must already exist."""
        s = self._seqs[seq_id]
        new_len = s["len"] + int(n_tokens)
        E.enforce(new_len <= len(s["pages"]) * self.page_size,
                  f"advance past capacity: {new_len} tokens > "
                  f"{len(s['pages'])} pages")
        s["len"] = new_len

    def fork(self, src_id: int, dst_id: int) -> List[int]:
        """Share src's pages with a new sequence (beam/top-k fork): pure
        refcount bumps, zero copies now; a later ``ensure`` on either
        side copy-on-writes the tail page."""
        E.enforce(dst_id not in self._seqs,
                  f"sequence {dst_id} already allocated")
        s = self._seqs[src_id]
        for p in s["pages"]:
            self._ref[p] += 1
        self._seqs[dst_id] = {"pages": list(s["pages"]), "len": s["len"]}
        return list(s["pages"])

    def free(self, seq_id: int):
        s = self._seqs.pop(seq_id)
        for p in s["pages"]:
            self._ref[p] -= 1
            E.enforce(self._ref[p] >= 0, f"double free of page {p}")
            if self._ref[p] == 0:
                self._free.append(p)


class _RadixNode:
    """One page of cached prefix: ``key`` is the page's token tuple,
    path-from-root is the page-aligned prefix it completes."""
    __slots__ = ("key", "page", "children", "parent", "stamp")

    def __init__(self, key, page, parent, stamp):
        self.key = key
        self.page = page
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.parent = parent
        self.stamp = stamp


class PrefixCache:
    """Radix tree over committed, page-aligned KV prefixes (vLLM
    automatic-prefix-caching / SGLang RadixAttention shape, at page
    granularity: one node per page, edge key = that page's token ids).

    Lifecycle contract with :class:`PageAllocator`:

    - ``insert`` runs at request retirement, BEFORE the sequence's
      ``free`` — only fully committed pages enter, each pinned with
      ``cache_hold`` (one extra ref owned by the cache).
    - ``match`` returns the longest cached prefix STRICTLY shorter than
      the prompt, page-aligned — admission always prefills >= 1 tail
      token because the first sampled token needs last-position logits.
      Matched nodes' LRU stamps refresh.
    - ``evict`` drops LRU leaves whose page no live sequence holds
      (``_ref == cache_hold``); releasing a live-held page would free
      nothing, so pinned leaves are skipped — the allocator audit
      (``check_invariants``) proves no shared-page free either way.

    Two sequences producing the same token path produce the same KV
    content (position-dependent rope included: same tokens at the same
    positions), so descending an existing node on insert keeps the
    cached copy — the same cross-shape determinism the ring/paged
    parity tests already pin.
    """

    def __init__(self, alloc: PageAllocator):
        self.alloc = alloc
        self.page_size = alloc.page_size
        self.root = _RadixNode(None, None, None, 0)
        self._clock = 0
        self._nodes = 0
        self.evicted_nodes = 0

    @property
    def nodes(self) -> int:
        return self._nodes

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _key(self, tokens, i: int) -> tuple:
        ps = self.page_size
        return tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])

    def match(self, tokens) -> Tuple[int, List[int]]:
        """Longest cached page-aligned prefix of ``tokens`` capped at
        ``len(tokens) - 1``: returns (n_cached_tokens, pages). Touches
        every matched node's LRU stamp."""
        limit = (len(tokens) - 1) // self.page_size
        node, pages = self.root, []
        stamp = self._tick()
        i = 0
        while i < limit:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            child.stamp = stamp
            pages.append(child.page)
            node = child
            i += 1
        return i * self.page_size, pages

    def insert(self, tokens, pages: List[int]) -> int:
        """Insert the committed page-aligned prefix of ``tokens`` (KV
        in ``pages``, the retiring sequence's block row). New nodes
        take a cache hold on their page; existing nodes keep the cached
        copy. Returns nodes added."""
        n_full = min(len(tokens) // self.page_size, len(pages))
        node, added = self.root, 0
        stamp = self._tick()
        for i in range(n_full):
            key = self._key(tokens, i)
            child = node.children.get(key)
            if child is None:
                self.alloc.cache_hold(pages[i])
                child = _RadixNode(key, pages[i], node, stamp)
                node.children[key] = child
                self._nodes += 1
                added += 1
            else:
                child.stamp = stamp
            node = child
        return added

    def reclaimable(self) -> int:
        """Pages eviction could return to the free list right now:
        cache-held pages whose ONLY refs are the cache's. Admission
        counts these as headroom — they are one ``evict`` away from
        free, so the watermark must not let them jam the pool."""
        a = self.alloc
        return int(np.sum((a._cache_hold > 0)
                          & (a._ref == a._cache_hold)))

    def evict(self, n_pages: int) -> int:
        """LRU leaf eviction until ``n_pages`` landed on the free list
        or nothing evictable remains. Only leaves whose page would
        actually free are dropped (interior nodes become leaves as
        their subtrees drain, so deep reclaimable pages cascade out).
        Returns pages freed."""
        a = self.alloc
        freed = 0
        while freed < n_pages:
            best = None
            stack = [self.root]
            while stack:
                nd = stack.pop()
                for ch in nd.children.values():
                    if ch.children:
                        stack.append(ch)
                    elif a._ref[ch.page] == a._cache_hold[ch.page] \
                            and (best is None or ch.stamp < best.stamp):
                        best = ch
            if best is None:
                break
            del best.parent.children[best.key]
            self._nodes -= 1
            self.evicted_nodes += 1
            freed += a.cache_release(best.page)
        return freed


# ---------------------------------------------------------------------------
# pool tensors
# ---------------------------------------------------------------------------

def init_pool(config, num_pages: int, page_size: int, dtype=None,
              kv_quant: bool = False) -> dict:
    """Fresh page pools, one [P, kv, ps, hd] grid per layer (stacked on
    a leading layer axis to ride the decode lax.scan, like the ring
    cache). With ``kv_quant`` (FLAGS_serving_kv_quant) each pool leaf
    is the quantized pair {"q": int8 codes, "s": f32 [L, P, kv] scale
    plane} — per-page per-kv-head write-time absmax scales ride the
    SAME page axis as their codes, so every page-granular operation
    (CoW copy, fork refcount, scatter-with-drop) moves code and scale
    rows together. Zero scale = untouched page, dequantizing to 0."""
    dt = dtype if dtype is not None else config.dtype
    shape = (config.num_hidden_layers, num_pages,
             config.num_key_value_heads, page_size, config.head_dim)
    if kv_quant:
        def leaf():
            return {"q": jnp.zeros(shape, jnp.int8),
                    "s": jnp.zeros(shape[:3], jnp.float32)}
        return {"k": leaf(), "v": leaf()}
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


class PagedKVCache:
    """Pool tensors + allocator under one roof — the serving engine's
    cache object. Device state lives in ``.pool`` (replaced wholesale by
    the jitted prefill/decode calls); control state in ``.alloc``."""

    def __init__(self, config, num_pages: int, page_size: int,
                 max_pages_per_seq: int, dtype=None,
                 kv_quant: bool = False):
        self.config = config
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.kv_quant = bool(kv_quant)
        self.pool = init_pool(config, num_pages, page_size, dtype,
                              kv_quant=self.kv_quant)
        self.alloc = PageAllocator(num_pages, page_size, max_pages_per_seq)
        # page-row copy over EVERY pool leaf: the quantized pool's
        # scale planes share the page axis (axis 1) with their codes,
        # so one tree_map mirrors CoW onto codes and scales exactly —
        # the invariant the fork/CoW scale tests pin
        self._copy1 = jax.jit(
            lambda pool, src, dst: jax.tree.map(
                lambda a: a.at[:, dst].set(a[:, src]), pool),
            donate_argnums=(0,))

    def apply_cow(self, pairs):
        """Mirror allocator copy-on-write decisions onto the device pool."""
        for src, dst in pairs:
            self.pool = self._copy1(self.pool,
                                    jnp.asarray(src), jnp.asarray(dst))

    def block_tables(self, seq_ids, width: Optional[int] = None
                     ) -> np.ndarray:
        """[len(seq_ids), width] block table; None entries (empty slots)
        become all-sentinel rows."""
        width = self.max_pages_per_seq if width is None else width
        rows = np.full((len(seq_ids), width), self.num_pages, np.int32)
        for i, sid in enumerate(seq_ids):
            if sid is not None:
                rows[i] = self.alloc.block_row(sid, width)
        return rows


# ---------------------------------------------------------------------------
# data plane (pure jax; family/config static under jit)
# ---------------------------------------------------------------------------

# int8 KV code range (FLAGS_serving_kv_quant). Scales are per-page
# per-kv-head write-time absmax/127 — symmetric, round-to-nearest, the
# same shape of contract as the weight-only scheme (llama.quant_int8)
# but chosen dynamically at every page write.
_KV_QMAX = 127.0


def _kv_quantize(xf, s):
    """int8 codes of f32 values under broadcastable scales ``s``."""
    return jnp.clip(jnp.round(xf / jnp.maximum(s, 1e-10)),
                    -_KV_QMAX, _KV_QMAX).astype(jnp.int8)


def _kv_pool_write(pool, pages, page_rows):
    """Scatter freshly computed whole-page grids ``pages``
    [L, ..., kv, ps, hd] into a pool leaf at ``page_rows`` with the
    drop discipline — quantizing in-program when the pool is the
    {"q", "s"} pair: scales are the written pages' own absmax (over
    the ps/hd axes, per kv head), and code + scale rows land under the
    SAME drop mask, so a sentinel row drops both."""
    if isinstance(pool, dict):
        xf = pages.astype(jnp.float32)
        s = jnp.max(jnp.abs(xf), axis=(-2, -1)) / _KV_QMAX
        q = _kv_quantize(xf, s[..., None, None])
        return {"q": pool["q"].at[:, page_rows].set(q, mode="drop"),
                "s": pool["s"].at[:, page_rows].set(s, mode="drop")}
    return pool.at[:, page_rows].set(pages.astype(pool.dtype),
                                     mode="drop")


def _kv_pool_gather(pool, rows, dtype):
    """Gather page rows from a pool leaf as [*rows.shape, kv, ps, hd]
    in ``dtype`` — dequantized (f32 multiply, ONE cast: the _mm seam
    ordering) when the pool is quantized."""
    if isinstance(pool, dict):
        deq = (pool["q"][rows].astype(jnp.float32)
               * pool["s"][rows][..., None, None])
        return deq.astype(dtype)
    return pool[rows].astype(dtype)


def _kv_page_append(leaf, rows, off, val, P):
    """Append one token's [B, kv, hd] values at slot ``off`` of pages
    ``rows`` (sentinel ``P`` drops) — the decode-step write. Quantized
    pools rescale the whole touched page: gather, dequantize, zero the
    not-yet-written tail slots (a reused page's stale codes must not
    inflate the scale), insert the token, requantize under the page's
    fresh absmax, and scatter codes + scale row under one drop mask.
    Committed slots re-round at most once per scale change — bounded
    by page_size writes, inside the decode-parity SQNR budget."""
    B, kv = val.shape[0], val.shape[1]
    kvi = jnp.arange(kv)
    if isinstance(leaf, dict):
        ps = leaf["q"].shape[2]
        rc = jnp.clip(rows, 0, P - 1)
        page = (leaf["q"][rc].astype(jnp.float32)
                * leaf["s"][rc][..., None, None])      # [B, kv, ps, hd]
        keep = jnp.arange(ps)[None, None, :, None] \
            <= off[:, None, None, None]
        page = jnp.where(keep, page, 0.0)
        page = page.at[jnp.arange(B)[:, None], kvi[None, :],
                       off[:, None]].set(val.astype(jnp.float32),
                                         unique_indices=True)
        s = jnp.max(jnp.abs(page), axis=(-2, -1)) / _KV_QMAX
        q = _kv_quantize(page, s[..., None, None])
        return {"q": leaf["q"].at[rows[:, None], kvi[None, :]].set(
                    q, mode="drop", unique_indices=True),
                "s": leaf["s"].at[rows[:, None], kvi[None, :]].set(
                    s, mode="drop", unique_indices=True)}
    return leaf.at[rows[:, None], kvi[None, :], off[:, None]].set(
        val.astype(leaf.dtype), mode="drop", unique_indices=True)


def paged_prefill(family, params, ids, config, pool_k, pool_v, page_rows,
                  slen):
    """Consume a batch of padded prompts [G, S_pad] (S_pad a page
    multiple; rows are INDEPENDENT requests): writes every covered page
    of K/V into ``page_rows`` [G, S_pad/ps] (sentinel rows drop —
    padding beyond a request's owned pages never lands; an all-sentinel
    row is a group-padding dummy) and returns (pool_k', pool_v', logits
    [G, V] at each row's position ``slen[g]``-1). Identical layer math
    to the family's ring-buffer prefill, so greedy decode parity holds
    token-for-token."""
    c = config
    G, S = ids.shape
    quant = isinstance(pool_k, dict)
    L, P, kv, ps, hd = (pool_k["q"] if quant else pool_k).shape
    E.enforce(S % ps == 0, f"padded prompt {S} not a multiple of "
              f"page_size {ps}")
    x = jnp.take(params["embed"], ids, axis=0)
    cos, sin = rope_tables(S, c.head_dim, theta=c.rope_theta)

    from ..nn.functional.attention import sdpa_raw

    def step(carry, lp):
        x = carry
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        a = sdpa_raw(q, k, v, is_causal=True).reshape(G, S, -1)
        x = x + _mm(a.astype(x.dtype), lp["wo"])
        return family.decode_mlp(x, lp, c), (k, v)

    x, (ks, vs) = lax.scan(step, x, params["layers"])
    npad = S // ps
    # [L, G, S, kv, hd] -> [L, G, npad, kv, ps, hd] page grids
    ks = jnp.moveaxis(ks.reshape(L, G, npad, ps, kv, hd), 4, 3)
    vs = jnp.moveaxis(vs.reshape(L, G, npad, ps, kv, hd), 4, 3)
    pool_k = _kv_pool_write(pool_k, ks, page_rows)
    pool_v = _kv_pool_write(pool_v, vs, page_rows)
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(slen - 1, 0)[:, None, None], axis=1)[:, 0]
    logits = _head_logits(last, family._head(params, c))
    return pool_k, pool_v, logits


def paged_decode_step(family, params, pool_k, pool_v, block_tables,
                      lengths, tokens, config):
    """One incremental step over the fixed slot grid. ``tokens`` [B]
    sit at position ``lengths``-1 of their sequences (``lengths`` is the
    valid KV count INCLUDING each new token; 0 marks an inactive slot —
    its write is dropped and its logits row is garbage the caller
    masks). Returns (pool_k', pool_v', logits [B, V])."""
    c = config
    B = tokens.shape[0]
    quant = isinstance(pool_k, dict)
    L, P, kv, ps, hd = (pool_k["q"] if quant else pool_k).shape
    maxp = block_tables.shape[1]
    n = lengths
    posw = jnp.maximum(n - 1, 0)                       # [B] write position
    x = jnp.take(params["embed"], tokens, axis=0)[:, None, :]
    # rope angles computed directly at the ragged positions (identical
    # floats to a rope_tables row: same product, same cos — but a fused
    # elementwise chain instead of two table gathers per step)
    inv = 1.0 / (c.rope_theta ** (
        jnp.arange(0, c.head_dim, 2, jnp.float32) / c.head_dim))
    freqs = posw.astype(jnp.float32)[:, None, None] * inv  # [B, 1, hd/2]
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)

    page_idx = posw // ps
    off = posw % ps
    rows = jnp.take_along_axis(block_tables, page_idx[:, None],
                               axis=1)[:, 0]
    rows = jnp.where(n > 0, rows, P)                   # inactive: drop
    kvi = jnp.arange(kv)

    from ..kernels import dispatched_paged_attention

    def step(carry, xs):
        x = carry
        lp, kpl, vpl = xs                              # [P, kv, ps, hd]
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        kpl = _kv_page_append(kpl, rows, off, k[:, 0], P)
        vpl = _kv_page_append(vpl, rows, off, v[:, 0], P)
        if quant:
            a = dispatched_paged_attention(
                q[:, 0], kpl["q"], vpl["q"], block_tables, n,
                k_scales=kpl["s"], v_scales=vpl["s"])
        else:
            a = dispatched_paged_attention(q[:, 0], kpl, vpl,
                                           block_tables, n)
        x = x + _mm(a.reshape(B, 1, -1).astype(x.dtype), lp["wo"])
        return family.decode_mlp(x, lp, c), (kpl, vpl)

    x, (kc, vc) = lax.scan(step, x, (params["layers"], pool_k, pool_v))
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    logits = _head_logits(x[:, 0, :], family._head(params, c))
    return kc, vc, logits


def paged_prefill_shared(family, params, ids, config, pool_k, pool_v,
                         page_rows, slen, ctx_rows):
    """Tail-only prefill over a SHARED cached prefix: every row owns
    ``ctx_rows`` [G, ncp] pages of committed prefix KV (the radix
    cache's, forked by refcount — all rows share the same static
    cached length ncp*ps) and prefills only its uncached tail ``ids``
    [G, S_tail] into ``page_rows`` (sentinel drops, as in
    ``paged_prefill``). Tail queries attend the gathered prefix pages
    plus causally within the tail, with rope at the true absolute
    positions, so logits at ``slen``-1 (tail-local) are identical to a
    full prefill at position ncp*ps+slen-1. Returns (pool_k', pool_v',
    logits [G, V])."""
    c = config
    G, S = ids.shape
    quant = isinstance(pool_k, dict)
    L, P, kv, ps, hd = (pool_k["q"] if quant else pool_k).shape
    ncp = ctx_rows.shape[1]
    E.enforce(S % ps == 0, f"padded tail {S} not a multiple of "
              f"page_size {ps}")
    E.enforce(ncp >= 1, "shared prefill needs a cached prefix")
    ctx = ncp * ps
    x = jnp.take(params["embed"], ids, axis=0)
    cos, sin = rope_tables(ctx + S, c.head_dim, theta=c.rope_theta)
    cos, sin = cos[ctx:], sin[ctx:]
    # key t (prefix ++ tail token-major) visible to tail query i iff
    # t <= ctx + i: the whole prefix, causal within the tail
    mask = (jnp.arange(ctx + S)[None, :]
            <= (jnp.arange(S)[:, None] + ctx))[None, None]

    from ..nn.functional.attention import sdpa_raw

    def step(carry, xs):
        x = carry
        lp, kpl, vpl = xs
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        # cached prefix pages, token-major: [G, ncp, kv, ps, hd] ->
        # [G, ctx, kv, hd] (rope already applied when they were
        # written; quantized pools dequantize in the gather)
        ck = jnp.swapaxes(_kv_pool_gather(kpl, ctx_rows, k.dtype),
                          2, 3).reshape(G, ctx, kv, hd)
        cv = jnp.swapaxes(_kv_pool_gather(vpl, ctx_rows, v.dtype),
                          2, 3).reshape(G, ctx, kv, hd)
        ka = jnp.concatenate([ck, k], axis=1)
        va = jnp.concatenate([cv, v], axis=1)
        a = sdpa_raw(q, ka, va, attn_mask=mask).reshape(G, S, -1)
        x = x + _mm(a.astype(x.dtype), lp["wo"])
        return family.decode_mlp(x, lp, c), (k, v)

    x, (ks, vs) = lax.scan(step, x, (params["layers"], pool_k, pool_v))
    npad = S // ps
    ks = jnp.moveaxis(ks.reshape(L, G, npad, ps, kv, hd), 4, 3)
    vs = jnp.moveaxis(vs.reshape(L, G, npad, ps, kv, hd), 4, 3)
    pool_k = _kv_pool_write(pool_k, ks, page_rows)
    pool_v = _kv_pool_write(pool_v, vs, page_rows)
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(slen - 1, 0)[:, None, None], axis=1)[:, 0]
    logits = _head_logits(last, family._head(params, c))
    return pool_k, pool_v, logits


def paged_verify_window(family, params, tokens, config, pool_k, pool_v,
                        block_tables, kv_len, live):
    """Speculative-decode verify: process a drafted window ``tokens``
    [B, C] sitting at positions ``kv_len``..``kv_len``+C-1 of each
    sequence in ONE forward pass — the window's KV is written into the
    block-table pages first (dropped where ``live`` is False), then
    every window query attends the sequence's full paged context plus
    causally within the window. C-fold fewer sequential model passes
    than C ``paged_decode_step`` calls; identical math per position, so
    greedy argmax over the returned logits [B, C, V] reproduces the
    sequential chunk token-for-token. The host accepts the longest
    draft-matching run and simply does not ``advance`` past it —
    rejected positions' KV is masked garbage until overwritten."""
    c = config
    B, C = tokens.shape
    quant = isinstance(pool_k, dict)
    L, P, kv, ps, hd = (pool_k["q"] if quant else pool_k).shape
    maxp = block_tables.shape[1]
    pos = kv_len[:, None] + jnp.arange(C)[None, :]          # [B, C]
    x = jnp.take(params["embed"], tokens, axis=0)
    inv = 1.0 / (c.rope_theta ** (
        jnp.arange(0, c.head_dim, 2, jnp.float32) / c.head_dim))
    freqs = pos.astype(jnp.float32)[:, :, None] * inv[None, None, :]
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)

    page_idx = pos // ps
    off = pos % ps
    rows = jnp.take_along_axis(block_tables, page_idx, axis=1)
    rows = jnp.where(live[:, None], rows, P)                # dead: drop
    kvi = jnp.arange(kv)
    # pool slot t (token-major over this row's block table) visible to
    # window query i iff t <= kv_len + i; slots past the allocated
    # pages gather clamped garbage and sit beyond every query's limit
    mask = jnp.arange(maxp * ps)[None, None, :] <= pos[:, :, None]

    # quantized pools rewrite the window's touched pages wholesale:
    # the window spans at most nwp consecutive pages per sequence
    # (worst case: first token at the last slot of its page)
    nwp = (C + ps - 2) // ps + 1
    wstart = kv_len // ps                                   # [B]
    wi = wstart[:, None] + jnp.arange(nwp)[None, :]         # [B, nwp]
    wrows = jnp.take_along_axis(block_tables,
                                jnp.clip(wi, 0, maxp - 1), axis=1)
    # past-the-table or dead rows: sentinel, scatter drops the page
    wrows = jnp.where((wi < maxp) & live[:, None], wrows, P)
    lpi = page_idx - wstart[:, None]                        # [B, C] local
    bi = jnp.arange(B)[:, None]

    def _window_rewrite(leaf, val):
        """Gather the window's nwp pages, dequantize, zero the
        not-yet-written tail (stale codes must not inflate the
        scale), insert the window tokens, requantize each page under
        its fresh absmax, scatter codes + scale rows back under one
        drop mask."""
        rc = jnp.clip(wrows, 0, P - 1)
        page = (leaf["q"][rc].astype(jnp.float32)
                * leaf["s"][rc][..., None, None])  # [B, nwp, kv, ps, hd]
        gpos = wi[:, :, None] * ps + jnp.arange(ps)[None, None, :]
        keep = gpos <= (kv_len + C - 1)[:, None, None]      # [B, nwp, ps]
        page = jnp.where(keep[:, :, None, :, None], page, 0.0)
        page = page.at[bi[:, :, None], lpi[:, :, None],
                       kvi[None, None, :], off[:, :, None]].set(
            val.astype(jnp.float32), unique_indices=True)
        s = jnp.max(jnp.abs(page), axis=(-2, -1)) / _KV_QMAX
        q = _kv_quantize(page, s[..., None, None])
        return {"q": leaf["q"].at[wrows[:, :, None],
                                  kvi[None, None, :]].set(
                    q, mode="drop", unique_indices=True),
                "s": leaf["s"].at[wrows[:, :, None],
                                  kvi[None, None, :]].set(
                    s, mode="drop", unique_indices=True)}

    from ..nn.functional.attention import sdpa_raw

    def step(carry, xs):
        x = carry
        lp, kpl, vpl = xs
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        if quant:
            kpl = _window_rewrite(kpl, k)
            vpl = _window_rewrite(vpl, v)
        else:
            kpl = kpl.at[rows[:, :, None], kvi[None, None, :],
                         off[:, :, None]].set(
                k.astype(kpl.dtype), mode="drop", unique_indices=True)
            vpl = vpl.at[rows[:, :, None], kvi[None, None, :],
                         off[:, :, None]].set(
                v.astype(vpl.dtype), mode="drop", unique_indices=True)
        ck = jnp.swapaxes(_kv_pool_gather(kpl, block_tables, q.dtype),
                          2, 3).reshape(B, maxp * ps, kv, hd)
        cv = jnp.swapaxes(_kv_pool_gather(vpl, block_tables, q.dtype),
                          2, 3).reshape(B, maxp * ps, kv, hd)
        a = sdpa_raw(q, ck, cv,
                     attn_mask=mask[:, None]).reshape(B, C, -1)
        x = x + _mm(a.astype(x.dtype), lp["wo"])
        return family.decode_mlp(x, lp, c), (kpl, vpl)

    x, (kc, vc) = lax.scan(step, x, (params["layers"], pool_k, pool_v))
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    logits = _head_logits(x, family._head(params, c))
    return kc, vc, logits
