"""Paged KV cache: page-pool tensors + block-table allocator + the
paged prefill/decode data plane.

Reference capability: vLLM's PagedAttention block manager (the
dominant serving-stack design: KV lives in fixed-size pages named by
per-sequence block tables, so HBM is allocated at page granularity
instead of max-length ring buffers) realised TPU-native per Ragged
Paged Attention (arxiv 2604.15464, PAPERS.md).

Three layers:

- ``PageAllocator`` — the host-side control plane: a free list plus
  ref-counted pages per sequence (alloc / ensure(+copy-on-write) /
  advance / fork / free). Pure Python+numpy; never touches the device.
- ``PagedKVCache`` — the pool tensors (one page grid per layer) married
  to an allocator; owns layout and the block-table/length device views.
- ``paged_prefill`` / ``paged_decode_step`` — pure-jax data plane with
  the same (params, ..., config) shape as the ring-buffer
  ``(init_cache, prefill, decode_step)`` contract in models/llama.py,
  but generic over the model family: any module exposing the decoder
  seam (``_qkv_proj``-compatible layers, ``decode_mlp``, ``_head``)
  plugs in — llama and the MoE families both do.

Pool layout: ``[L, num_pages, kv_heads, page_size, head_dim]``. The
ISSUE/vLLM order puts page_size before kv_heads; the kv-head axis is
hoisted OUTSIDE the page axis here so the decode kernel's per-page
block ``(1, 1, page_size, head_dim)`` satisfies Mosaic's last-two-dims
tiling rule for every page size (see kernels/paged_attention.py).

Writes into pages use scatter-with-drop: block-table entries equal to
``num_pages`` are an explicit "no page" sentinel, so a padded prompt
page or an inactive decode slot drops its write instead of corrupting
page 0 — the allocator owns the sentinel discipline.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import enforce as E
from ..models.llama import _head_logits, _mm, _qkv_proj, _rms
from ..nn.functional.attention import rope_raw, rope_tables

__all__ = ["PageAllocator", "PagedKVCache", "init_pool",
           "paged_prefill", "paged_decode_step"]


# ---------------------------------------------------------------------------
# host-side control plane
# ---------------------------------------------------------------------------

class PageAllocator:
    """Free-list page allocator with per-sequence block tables and
    ref-counted pages (copy-on-fork for beam/top-k style sequence
    sharing). All methods are host-side and O(pages touched); OOM is a
    ``None`` return with state unchanged — admission control, not an
    exception."""

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_seq: int):
        E.enforce(num_pages >= 1, f"num_pages must be >= 1, got {num_pages}")
        E.enforce(page_size >= 1, f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._ref = np.zeros(num_pages, np.int32)
        # seq_id -> {"pages": [page ids], "len": tokens written}
        self._seqs: Dict[int, dict] = {}

    # -- introspection ------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_size)

    def seq_len(self, seq_id: int) -> int:
        return self._seqs[seq_id]["len"]

    def seq_pages(self, seq_id: int) -> List[int]:
        return list(self._seqs[seq_id]["pages"])

    def page_count(self, seq_id: int) -> int:
        """Pages currently held by this sequence (no list copy — the
        engine's per-chunk cost attribution reads it per live slot)."""
        return len(self._seqs[seq_id]["pages"])

    def block_row(self, seq_id: int, width: Optional[int] = None
                  ) -> np.ndarray:
        """This sequence's block-table row, padded with the ``num_pages``
        sentinel (the no-page value the scatter path drops)."""
        width = self.max_pages_per_seq if width is None else width
        row = np.full(width, self.num_pages, np.int32)
        pages = self._seqs[seq_id]["pages"]
        row[:len(pages)] = pages
        return row

    def check_invariants(self):
        """Refcount bookkeeping audit (tests): every page is either free
        (ref 0) or referenced exactly as many times as sequences hold
        it, and the free list is duplicate-free."""
        counts = np.zeros(self.num_pages, np.int32)
        for s in self._seqs.values():
            for p in s["pages"]:
                counts[p] += 1
        if not np.array_equal(counts, self._ref):
            raise AssertionError(
                f"refcount drift: held={counts.tolist()} "
                f"ref={self._ref.tolist()}")
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages on the free list")
        if any(self._ref[p] != 0 for p in free):
            raise AssertionError("referenced page on the free list")
        if len(free) + int((self._ref > 0).sum()) != self.num_pages:
            raise AssertionError("leaked page: neither free nor referenced")

    # -- lifecycle ----------------------------------------------------------

    def _take(self, n: int) -> Optional[List[int]]:
        if len(self._free) < n:
            return None
        taken = [self._free.pop() for _ in range(n)]
        for p in taken:
            self._ref[p] += 1
        return taken

    def alloc(self, seq_id: int, n_tokens: int) -> Optional[List[int]]:
        """Create a sequence with capacity for ``n_tokens`` (its written
        length starts at 0 — ``advance`` after the KV lands). None = OOM."""
        E.enforce(seq_id not in self._seqs,
                  f"sequence {seq_id} already allocated")
        need = self.pages_for(n_tokens)
        E.enforce(need <= self.max_pages_per_seq,
                  f"{n_tokens} tokens need {need} pages > "
                  f"max_pages_per_seq {self.max_pages_per_seq}")
        pages = self._take(need)
        if pages is None:
            return None
        self._seqs[seq_id] = {"pages": pages, "len": 0}
        return pages

    def ensure(self, seq_id: int, total_tokens: int
               ) -> Optional[Tuple[List[int], List[Tuple[int, int]]]]:
        """Grow capacity to ``total_tokens`` and copy-on-write any SHARED
        page the upcoming writes (positions >= current len) would touch.
        Returns (new_pages, cow_pairs[(src, dst)]) — the caller must
        mirror cow_pairs onto the device pool — or None on OOM (state
        unchanged)."""
        s = self._seqs[seq_id]
        need_total = self.pages_for(total_tokens)
        E.enforce(need_total <= self.max_pages_per_seq,
                  f"{total_tokens} tokens need {need_total} pages > "
                  f"max_pages_per_seq {self.max_pages_per_seq}")
        grow = max(0, need_total - len(s["pages"]))
        first_written = s["len"] // self.page_size
        cow_idx = [i for i in range(first_written,
                                    min(len(s["pages"]), need_total))
                   if self._ref[s["pages"][i]] > 1]
        fresh = self._take(grow + len(cow_idx))
        if fresh is None:
            return None
        new_pages, cow_dst = fresh[:grow], fresh[grow:]
        cow_pairs = []
        for i, dst in zip(cow_idx, cow_dst):
            src = s["pages"][i]
            cow_pairs.append((src, dst))
            self._ref[src] -= 1          # shared: never hits 0 here
            s["pages"][i] = dst
        s["pages"].extend(new_pages)
        return new_pages, cow_pairs

    def advance(self, seq_id: int, n_tokens: int = 1):
        """Record ``n_tokens`` written; capacity must already exist."""
        s = self._seqs[seq_id]
        new_len = s["len"] + int(n_tokens)
        E.enforce(new_len <= len(s["pages"]) * self.page_size,
                  f"advance past capacity: {new_len} tokens > "
                  f"{len(s['pages'])} pages")
        s["len"] = new_len

    def fork(self, src_id: int, dst_id: int) -> List[int]:
        """Share src's pages with a new sequence (beam/top-k fork): pure
        refcount bumps, zero copies now; a later ``ensure`` on either
        side copy-on-writes the tail page."""
        E.enforce(dst_id not in self._seqs,
                  f"sequence {dst_id} already allocated")
        s = self._seqs[src_id]
        for p in s["pages"]:
            self._ref[p] += 1
        self._seqs[dst_id] = {"pages": list(s["pages"]), "len": s["len"]}
        return list(s["pages"])

    def free(self, seq_id: int):
        s = self._seqs.pop(seq_id)
        for p in s["pages"]:
            self._ref[p] -= 1
            E.enforce(self._ref[p] >= 0, f"double free of page {p}")
            if self._ref[p] == 0:
                self._free.append(p)


# ---------------------------------------------------------------------------
# pool tensors
# ---------------------------------------------------------------------------

def init_pool(config, num_pages: int, page_size: int, dtype=None) -> dict:
    """Fresh page pools, one [P, kv, ps, hd] grid per layer (stacked on
    a leading layer axis to ride the decode lax.scan, like the ring
    cache)."""
    dt = dtype if dtype is not None else config.dtype
    shape = (config.num_hidden_layers, num_pages,
             config.num_key_value_heads, page_size, config.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


class PagedKVCache:
    """Pool tensors + allocator under one roof — the serving engine's
    cache object. Device state lives in ``.pool`` (replaced wholesale by
    the jitted prefill/decode calls); control state in ``.alloc``."""

    def __init__(self, config, num_pages: int, page_size: int,
                 max_pages_per_seq: int, dtype=None):
        self.config = config
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.pool = init_pool(config, num_pages, page_size, dtype)
        self.alloc = PageAllocator(num_pages, page_size, max_pages_per_seq)
        self._copy1 = jax.jit(
            lambda pool, src, dst: {
                "k": pool["k"].at[:, dst].set(pool["k"][:, src]),
                "v": pool["v"].at[:, dst].set(pool["v"][:, src]),
            }, donate_argnums=(0,))

    def apply_cow(self, pairs):
        """Mirror allocator copy-on-write decisions onto the device pool."""
        for src, dst in pairs:
            self.pool = self._copy1(self.pool,
                                    jnp.asarray(src), jnp.asarray(dst))

    def block_tables(self, seq_ids, width: Optional[int] = None
                     ) -> np.ndarray:
        """[len(seq_ids), width] block table; None entries (empty slots)
        become all-sentinel rows."""
        width = self.max_pages_per_seq if width is None else width
        rows = np.full((len(seq_ids), width), self.num_pages, np.int32)
        for i, sid in enumerate(seq_ids):
            if sid is not None:
                rows[i] = self.alloc.block_row(sid, width)
        return rows


# ---------------------------------------------------------------------------
# data plane (pure jax; family/config static under jit)
# ---------------------------------------------------------------------------

def paged_prefill(family, params, ids, config, pool_k, pool_v, page_rows,
                  slen):
    """Consume a batch of padded prompts [G, S_pad] (S_pad a page
    multiple; rows are INDEPENDENT requests): writes every covered page
    of K/V into ``page_rows`` [G, S_pad/ps] (sentinel rows drop —
    padding beyond a request's owned pages never lands; an all-sentinel
    row is a group-padding dummy) and returns (pool_k', pool_v', logits
    [G, V] at each row's position ``slen[g]``-1). Identical layer math
    to the family's ring-buffer prefill, so greedy decode parity holds
    token-for-token."""
    c = config
    G, S = ids.shape
    L, P, kv, ps, hd = pool_k.shape
    E.enforce(S % ps == 0, f"padded prompt {S} not a multiple of "
              f"page_size {ps}")
    x = jnp.take(params["embed"], ids, axis=0)
    cos, sin = rope_tables(S, c.head_dim, theta=c.rope_theta)

    from ..nn.functional.attention import sdpa_raw

    def step(carry, lp):
        x = carry
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        a = sdpa_raw(q, k, v, is_causal=True).reshape(G, S, -1)
        x = x + _mm(a.astype(x.dtype), lp["wo"])
        return family.decode_mlp(x, lp, c), (k, v)

    x, (ks, vs) = lax.scan(step, x, params["layers"])
    npad = S // ps
    # [L, G, S, kv, hd] -> [L, G, npad, kv, ps, hd] page grids
    ks = jnp.moveaxis(ks.reshape(L, G, npad, ps, kv, hd), 4, 3)
    vs = jnp.moveaxis(vs.reshape(L, G, npad, ps, kv, hd), 4, 3)
    pool_k = pool_k.at[:, page_rows].set(ks.astype(pool_k.dtype),
                                         mode="drop")
    pool_v = pool_v.at[:, page_rows].set(vs.astype(pool_v.dtype),
                                         mode="drop")
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(slen - 1, 0)[:, None, None], axis=1)[:, 0]
    logits = _head_logits(last, family._head(params, c))
    return pool_k, pool_v, logits


def paged_decode_step(family, params, pool_k, pool_v, block_tables,
                      lengths, tokens, config):
    """One incremental step over the fixed slot grid. ``tokens`` [B]
    sit at position ``lengths``-1 of their sequences (``lengths`` is the
    valid KV count INCLUDING each new token; 0 marks an inactive slot —
    its write is dropped and its logits row is garbage the caller
    masks). Returns (pool_k', pool_v', logits [B, V])."""
    c = config
    B = tokens.shape[0]
    L, P, kv, ps, hd = pool_k.shape
    maxp = block_tables.shape[1]
    n = lengths
    posw = jnp.maximum(n - 1, 0)                       # [B] write position
    x = jnp.take(params["embed"], tokens, axis=0)[:, None, :]
    # rope angles computed directly at the ragged positions (identical
    # floats to a rope_tables row: same product, same cos — but a fused
    # elementwise chain instead of two table gathers per step)
    inv = 1.0 / (c.rope_theta ** (
        jnp.arange(0, c.head_dim, 2, jnp.float32) / c.head_dim))
    freqs = posw.astype(jnp.float32)[:, None, None] * inv  # [B, 1, hd/2]
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)

    page_idx = posw // ps
    off = posw % ps
    rows = jnp.take_along_axis(block_tables, page_idx[:, None],
                               axis=1)[:, 0]
    rows = jnp.where(n > 0, rows, P)                   # inactive: drop
    kvi = jnp.arange(kv)

    from ..kernels import dispatched_paged_attention

    def step(carry, xs):
        x = carry
        lp, kpl, vpl = xs                              # [P, kv, ps, hd]
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        kpl = kpl.at[rows[:, None], kvi[None, :], off[:, None]].set(
            k[:, 0].astype(kpl.dtype), mode="drop", unique_indices=True)
        vpl = vpl.at[rows[:, None], kvi[None, :], off[:, None]].set(
            v[:, 0].astype(vpl.dtype), mode="drop", unique_indices=True)
        a = dispatched_paged_attention(q[:, 0], kpl, vpl, block_tables, n)
        x = x + _mm(a.reshape(B, 1, -1).astype(x.dtype), lp["wo"])
        return family.decode_mlp(x, lp, c), (kpl, vpl)

    x, (kc, vc) = lax.scan(step, x, (params["layers"], pool_k, pool_v))
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    logits = _head_logits(x[:, 0, :], family._head(params, c))
    return kc, vc, logits
