"""Exactly-once request failover: admission journal, stranded-work
re-dispatch, poison-request quarantine, per-replica circuit breakers.

ROADMAP item 5 closed the loop for *training* rank loss (PR 14); this
module does it for serving. The elastic controller already detects a
dead replica and replaces it, but every request that replica had
admitted was simply typed ``lost`` by the replay accounting — nothing
anywhere re-dispatched it. The durability discipline here converts
"stranded work is typed lost" into "lost is a bug the bench guard
catches":

- **Admission journal** (:class:`AdmissionJournal`): every request an
  engine accepts is recorded — idempotency key, tenant, priority,
  deadline TTL, prompt spec (derivation seed) or inline tokens, pinned
  PRNG key, attempt count — on the fleet's existing name-keyed
  heartbeat transport (``distributed/heartbeat.py``), under the
  participant name ``<replica>.journal``. Completion markers are
  written at retirement, so a request that finished just before the
  crash is never double-served: re-dispatch skips any rid with a
  marker (the dedup is pinned by test).
- **Stranded-work re-dispatch** (:class:`FailoverCoordinator`): when
  the controller tombstones a replica, the coordinator reads its
  journal, skips completed markers, and queues the in-flight remainder
  for resubmission through the NORMAL admission path on survivors —
  remaining deadline carried, attempts bounded, backoff riding the
  demand-model ``retry_after_s`` hint (capped; an idle fleet's hint
  can reach 2x the autoscale horizon and must not stall recovery).
  Every stranded request ends in exactly one terminal state
  (``completed``/``expired``/``shed``/``quarantined``) with a
  ``recovered_from`` lineage instead of ``lost``.
- **Poison-request quarantine**: a request whose replica dies N
  consecutive attempts terminates typed ``quarantined`` (content-hash
  keyed, the ``training/sentinel.py`` batch-quarantine template)
  rather than cascading kills across the fleet.
- **Circuit breakers** (:class:`CircuitBreaker`): a replica that
  repeatedly sheds fresh admissions trips open, routes new work away
  for a cooldown, then half-opens with a single probe — close on
  success, reopen on failure.

Everything is flag-gated behind ``FLAGS_serving_failover`` (default
off); with the flag off no journal is attached, no coordinator exists,
and scheduling decisions plus emitted tokens are byte-identical to the
pre-failover tree.
"""
from __future__ import annotations

import hashlib
import os
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from .. import monitor as _monitor
from ..monitor import trace as _trace
from ..monitor import forensics as _forensics

JOURNAL_KIND = "paddle_tpu.admission_journal"
JOURNAL_VERSION = 1
JOURNAL_SUFFIX = ".journal"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def request_fingerprint(prompt, max_new_tokens, temperature) -> str:
    """Content hash for the poison-request quarantine set: a request
    that keeps killing replicas is identified by WHAT it asks for, not
    by its rid (a client retrying under a fresh rid must still hit the
    quarantine). blake2b-128, the ``training/sentinel.py`` batch-hash
    template."""
    h = hashlib.blake2b(digest_size=16)
    arr = np.ascontiguousarray(np.asarray(prompt, np.int32))
    h.update(arr.tobytes())
    h.update(str(int(max_new_tokens)).encode())
    h.update(repr(float(temperature)).encode())
    return h.hexdigest()


def journal_name(replica: str) -> str:
    return f"{replica}{JOURNAL_SUFFIX}"


class AdmissionJournal:
    """Write-through durability record for one replica's admitted
    requests, published on the name-keyed heartbeat transport under
    ``<replica>.journal`` (a name the controller never lists in its
    staleness scans, so the extra beat file is inert to liveness).

    The payload IS the journal: one publish per admit and per
    retirement keeps the transport copy current, so whatever the
    coordinator reads after a crash is at worst one event stale — and
    the completion marker for a request is written BEFORE its output
    is harvested, so "finished just before the crash" is always
    visible as completed, never re-served. Transport failures degrade
    honestly: the engine keeps serving and the affected requests fall
    back to today's ``lost`` typing."""

    def __init__(self, replica: str, *, dir_path: Optional[str] = None,
                 client=None, max_completed: int = 256):
        self.replica = str(replica)
        self._dir = dir_path
        self._client = client
        self._seq = 0
        self.inflight: Dict[str, dict] = {}
        # bounded completion-marker window (OrderedDict eviction): the
        # dedup only has to cover the crash window, not all history
        self.completed: "OrderedDict[str, dict]" = OrderedDict()
        self._max_completed = int(max_completed)
        self.publish_failures = 0

    # -- record construction ------------------------------------------------

    def _record(self, req) -> dict:
        prompt = np.asarray(getattr(req, "prompt"), np.int32)
        max_new = int(getattr(req, "max_new_tokens"))
        temp = float(getattr(req, "temperature", 0.0) or 0.0)
        rec = {
            "rid": int(getattr(req, "rid")),
            "tenant": str(getattr(req, "tenant", "default") or "default"),
            "priority": int(getattr(req, "priority", 0) or 0),
            "deadline_s": getattr(req, "deadline_s", None),
            "max_new_tokens": max_new,
            "temperature": temp,
            "attempts": int(getattr(req, "_failover_attempts", 0)),
            "recovered_from": list(getattr(req, "_recovered_from", ())),
        }
        fp = request_fingerprint(prompt, max_new, temp)
        rec["fingerprint"] = fp
        rec["idem"] = f"{rec['rid']}:{fp}"
        spec = getattr(req, "prompt_spec", None)
        if spec:
            # derivation spec (trace seed + rid + lengths): the replay
            # rebuilds the exact prompt as a pure function, keeping the
            # journal payload small for long prompts
            rec["prompt_spec"] = dict(spec)
        else:
            rec["prompt"] = [int(t) for t in prompt.tolist()]
        key = getattr(req, "key", None)
        if key is not None:
            k = np.asarray(key, np.uint32).reshape(-1)
            rec["key"] = [int(v) for v in k.tolist()]
        return rec

    # -- write-through events -----------------------------------------------

    def admit(self, req) -> None:
        rec = self._record(req)
        self.inflight[str(rec["rid"])] = rec
        _monitor.inc("serving.failover.journal.records",
                     doc="admission-journal records published (one per "
                         "accepted request while FLAGS_serving_failover "
                         "is on)")
        self._publish()

    def finish(self, rid, state: str, tokens: int = 0) -> None:
        rid_s = str(int(rid))
        rec = self.inflight.pop(rid_s, None)
        marker = {"state": str(state), "tokens": int(tokens)}
        if rec is not None:
            marker["idem"] = rec.get("idem")
        self.completed[rid_s] = marker
        while len(self.completed) > self._max_completed:
            self.completed.popitem(last=False)
        _monitor.inc("serving.failover.journal.completions",
                     doc="completion markers written at retirement "
                         "(the exactly-once dedup record)")
        self._publish()

    def _publish(self) -> None:
        from ..distributed import heartbeat as _hb
        self._seq += 1
        payload = {"kind": JOURNAL_KIND, "v": JOURNAL_VERSION,
                   "replica": self.replica, "seq": self._seq,
                   "inflight": self.inflight,
                   "completed": dict(self.completed)}
        try:
            ok = _hb.publish_named(journal_name(self.replica), payload,
                                   dir_path=self._dir,
                                   client=self._client)
        except Exception:
            ok = False
        if not ok:
            self.publish_failures += 1


def read_journal(replica: str, *, dir_path: Optional[str] = None,
                 client=None) -> Optional[dict]:
    """Best surviving journal payload for ``replica`` (file beat +
    coordination-service KV, seq tiebreak — ``read_named`` semantics),
    or None when absent/malformed. Never raises."""
    from ..distributed import heartbeat as _hb
    try:
        payload = _hb.read_named(journal_name(replica),
                                 dir_path=dir_path, client=client)
    except Exception:
        return None
    if not isinstance(payload, dict):
        return None
    if payload.get("kind") != JOURNAL_KIND:
        return None
    try:
        if int(payload.get("v", 0)) > JOURNAL_VERSION:
            return None  # refuse to half-parse a future format
    except (TypeError, ValueError):
        return None
    return payload


def sweep_journal(replica: str, *, dir_path: Optional[str] = None,
                  client=None) -> None:
    from ..distributed import heartbeat as _hb
    try:
        _hb.remove_named(dir_path, journal_name(replica), client=client)
    except Exception:
        pass


class CircuitBreaker:
    """Per-replica fresh-admission breaker: ``closed`` until
    ``threshold`` CONSECUTIVE shed admissions, then ``open`` for
    ``cooldown_s`` (new work routes away), then ``half_open`` with a
    single probe — success closes, failure reopens. Clock is passed
    in (the replay drives it with virtual time)."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 2.0):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"
        self.failures = 0
        self.opened_count = 0
        self.closed_count = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def allows(self, now: float) -> bool:
        if self.state == "open" and (now - self._opened_at
                                     >= self.cooldown_s):
            self.state = "half_open"
            self._probe_inflight = False
        if self.state == "closed":
            return True
        if self.state == "half_open":
            return not self._probe_inflight
        return False

    def note_probe(self) -> None:
        if self.state == "half_open":
            self._probe_inflight = True

    def record(self, ok: bool, now: float) -> None:
        if self.state == "half_open":
            self._probe_inflight = False
            if ok:
                self.state = "closed"
                self.failures = 0
                self.closed_count += 1
            else:
                self.state = "open"
                self._opened_at = now
                self.opened_count += 1
            return
        if ok:
            self.failures = 0
            return
        self.failures += 1
        if self.state == "closed" and self.failures >= self.threshold:
            self.state = "open"
            self._opened_at = now
            self.opened_count += 1

    def as_dict(self) -> dict:
        return {"state": self.state, "failures": self.failures,
                "opened": self.opened_count, "closed": self.closed_count}


class FailoverCoordinator:
    """Controller-side half of the durability discipline: consumes the
    journals of replaced replicas, owns the re-dispatch queue with
    bounded attempts + capped backoff, the quarantine hash set, and
    the per-replica circuit breakers. Lives on the elastic controller
    thread (``run_serving``) — no locking; the replay pump and the
    stale-replace path already share that thread by design.

    Knobs (env, read at construction):

    - ``PADDLE_TPU_FAILOVER_QUARANTINE_ATTEMPTS`` (default 3): a
      request stranded by this many replica deaths is quarantined.
    - ``PADDLE_TPU_FAILOVER_MAX_ATTEMPTS`` (default 6): total dispatch
      attempts (strands + shed retries) before a typed terminal shed.
    - ``PADDLE_TPU_FAILOVER_BACKOFF_CAP_S`` (default 5.0): ceiling on
      the re-dispatch backoff, including ``retry_after_s`` hints.
    - ``PADDLE_TPU_FAILOVER_BREAKER_THRESHOLD`` / ``..._COOLDOWN_S``
      (default 3 / 2.0): breaker trip point and open dwell."""

    def __init__(self, *, heartbeat_dir: Optional[str] = None,
                 client=None,
                 quarantine_attempts: Optional[int] = None,
                 max_attempts: Optional[int] = None,
                 backoff_cap_s: Optional[float] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_s: Optional[float] = None):
        self._dir = heartbeat_dir
        self._client = client
        self.quarantine_attempts = max(1, int(
            quarantine_attempts if quarantine_attempts is not None
            else _env_int("PADDLE_TPU_FAILOVER_QUARANTINE_ATTEMPTS", 3)))
        self.max_attempts = max(1, int(
            max_attempts if max_attempts is not None
            else _env_int("PADDLE_TPU_FAILOVER_MAX_ATTEMPTS", 6)))
        self.backoff_cap_s = max(0.0, float(
            backoff_cap_s if backoff_cap_s is not None
            else _env_float("PADDLE_TPU_FAILOVER_BACKOFF_CAP_S", 5.0)))
        self._breaker_threshold = max(1, int(
            breaker_threshold if breaker_threshold is not None
            else _env_int("PADDLE_TPU_FAILOVER_BREAKER_THRESHOLD", 3)))
        self._breaker_cooldown = float(
            breaker_cooldown_s if breaker_cooldown_s is not None
            else _env_float("PADDLE_TPU_FAILOVER_BREAKER_COOLDOWN_S",
                            2.0))
        # the coordinator's time source: every not_before/backoff stamp
        # and every due() comparison read the SAME clock. The replay
        # pump swaps in its virtual clock so backoff is deterministic
        # in virtual seconds, not wall time.
        self.clock = time.monotonic
        self.pending: List[dict] = []      # stranded, awaiting re-dispatch
        self.terminal: Dict[int, dict] = {}  # rid -> rec with "state"
        self.quarantined_hashes: set = set()
        self.breakers: Dict[str, CircuitBreaker] = {}
        self._redispatched: Dict[int, dict] = {}  # rid -> rec, in flight
        self.counters = {"stranded": 0, "redispatched": 0,
                         "recovered": 0, "quarantined": 0, "deduped": 0,
                         "expired": 0, "shed": 0, "attempts": 0}

    # -- strand intake ------------------------------------------------------

    def _backoff(self, attempts: int) -> float:
        base = 0.25 * (2.0 ** max(0, int(attempts) - 1))
        return min(self.backoff_cap_s, base)

    def _finish(self, rec: dict, state: str) -> None:
        rid = int(rec["rid"])
        rec = dict(rec, state=state)
        self.terminal[rid] = rec
        self.counters[state if state in self.counters else "shed"] = \
            self.counters.get(state, 0) + 1
        _trace.instant("serving.failover.terminal", rid=rid, state=state,
                       attempts=rec.get("attempts", 0))
        if state in ("quarantined", "expired", "shed"):
            # coordinator-terminated strands never reach an engine
            # terminal — this is their one terminal timeline event
            # (engine-terminated states already recorded theirs)
            _forensics.note_terminal(
                rid, state, attempts=rec.get("attempts", 0),
                tenant=rec.get("tenant"),
                recovered_from=list(rec.get("recovered_from") or []))

    def note_replaced(self, victim: str,
                      now: Optional[float] = None) -> int:
        """The controller replaced ``victim``: read its journal, skip
        every rid with a completion marker (the exactly-once dedup),
        quarantine poison requests, queue the rest for re-dispatch
        with backoff. Sweeps the journal and drops the breaker.
        Returns the number of requests stranded (queued or
        quarantined)."""
        now = self.clock() if now is None else now
        payload = read_journal(victim, dir_path=self._dir,
                               client=self._client)
        sweep_journal(victim, dir_path=self._dir, client=self._client)
        self.breakers.pop(victim, None)
        if payload is None:
            return 0
        completed = payload.get("completed") or {}
        # pending/terminal rids are settled elsewhere; a rid in
        # _redispatched is NOT skipped — its survivor just died too,
        # and this journal read is exactly its re-strand
        known = ({int(r["rid"]) for r in self.pending}
                 | set(self.terminal))
        stranded = 0
        for rid_s, rec in sorted((payload.get("inflight") or {}).items(),
                                 key=lambda kv: int(kv[1].get("rid", 0))):
            if not isinstance(rec, dict) or "rid" not in rec:
                continue
            rid = int(rec["rid"])
            if rid_s in completed:
                # finished just before the crash: the marker wins, the
                # output was (or will be) harvested — never re-serve
                self.counters["deduped"] += 1
                _monitor.inc("serving.failover.deduped",
                             doc="stranded rids skipped by a journal "
                                 "completion marker (exactly-once "
                                 "dedup)")
                continue
            if rid in known:
                continue
            self._redispatched.pop(rid, None)
            attempts = int(rec.get("attempts", 0)) + 1
            rec = dict(rec, attempts=attempts, t_strand=now,
                       # wall-clock strand stamp for the timing-plane
                       # recovery_s (never journaled — t_strand rides
                       # the coordinator clock, this one real time)
                       _t_strand_wall=time.perf_counter(),
                       recovered_from=list(rec.get("recovered_from")
                                           or []) + [victim])
            stranded += 1
            self.counters["stranded"] += 1
            _monitor.inc("serving.failover.stranded",
                         doc="journaled in-flight requests found on a "
                             "replaced replica")
            fp = rec.get("fingerprint")
            if ((fp and fp in self.quarantined_hashes)
                    or attempts >= self.quarantine_attempts):
                if fp:
                    self.quarantined_hashes.add(fp)
                _monitor.inc("serving.failover.quarantined",
                             doc="poison requests terminated typed "
                                 "`quarantined` after N consecutive "
                                 "replica-death attempts")
                self._finish(rec, "quarantined")
            else:
                rec["not_before"] = now + self._backoff(attempts)
                self.pending.append(rec)
            _trace.instant("serving.failover.strand", rid=rid,
                           replica=victim, attempts=attempts)
            # the strand hop rides the journal record's lineage, so a
            # recovered request's timeline spans replicas
            _forensics.note(rid, "strand",
                            t=rec["_t_strand_wall"], replica=victim,
                            attempts=attempts,
                            recovered_from=list(rec["recovered_from"]))
        _monitor.set_gauge("serving.failover.pending",
                           len(self.pending),
                           doc="stranded requests awaiting re-dispatch")
        return stranded

    # -- re-dispatch queue --------------------------------------------------

    def due(self, now: float) -> List[dict]:
        """Pop every stranded record whose backoff has elapsed. The
        caller must route each through ``redispatched``, ``requeue``
        or ``resolve`` — a popped record is no longer pending."""
        ready = [r for r in self.pending if r.get("not_before", 0.0)
                 <= now]
        if ready:
            self.pending = [r for r in self.pending
                            if r.get("not_before", 0.0) > now]
        return ready

    def redispatched(self, rec: dict, replica: str, now: float) -> None:
        rid = int(rec["rid"])
        self._redispatched[rid] = rec
        self.counters["redispatched"] += 1
        self.counters["attempts"] += 1
        _monitor.inc("serving.failover.redispatched",
                     doc="stranded requests resubmitted through normal "
                         "admission on a surviving replica")
        _trace.instant("serving.failover.redispatch", rid=rid,
                       replica=replica, attempts=rec.get("attempts", 0))
        _forensics.note(rid, "redispatch", replica=replica,
                        attempts=rec.get("attempts", 0))

    def requeue(self, rec: dict, now: float,
                retry_after_s: Optional[float] = None) -> None:
        """A re-dispatch attempt was shed by the survivor: back off on
        the (capped) ``retry_after_s`` hint and try again, until the
        total-attempt bound turns it into a typed terminal shed."""
        rid = int(rec["rid"])
        self._redispatched.pop(rid, None)
        self.counters["attempts"] += 1
        attempts = int(rec.get("attempts", 0)) + 1
        rec = dict(rec, attempts=attempts)
        if attempts >= self.max_attempts:
            self._finish(rec, "shed")
            return
        hint = self._backoff(attempts)
        if retry_after_s is not None:
            try:
                hint = min(self.backoff_cap_s,
                           max(0.0, float(retry_after_s)))
            except (TypeError, ValueError):
                pass
        rec["not_before"] = now + hint
        self.pending.append(rec)

    def resolve(self, rec: dict, state: str) -> None:
        """Terminal-state a stranded record without re-dispatching it
        (deadline spent while stranded -> ``expired``)."""
        self._redispatched.pop(int(rec["rid"]), None)
        if state == "expired":
            _monitor.inc("serving.failover.expired",
                         doc="stranded requests whose deadline was "
                             "already spent at re-dispatch time")
        self._finish(rec, state)

    def note_result(self, rid: int, state: str) -> None:
        """A re-dispatched request reached a terminal engine state on
        its survivor (the replay harvest observed the output)."""
        rec = self._redispatched.pop(int(rid), None)
        if rec is None:
            return
        if state == "completed":
            self.counters["recovered"] += 1
            _monitor.inc("serving.failover.recovered",
                         doc="stranded requests that COMPLETED on a "
                             "surviving replica after re-dispatch")

    def outstanding(self) -> int:
        return len(self.pending)

    # -- circuit breakers ---------------------------------------------------

    def _breaker(self, replica: str) -> CircuitBreaker:
        b = self.breakers.get(replica)
        if b is None:
            b = CircuitBreaker(self._breaker_threshold,
                               self._breaker_cooldown)
            self.breakers[replica] = b
        return b

    def pick_replica(self, live: List[str], rid: int,
                     now: float = 0.0) -> Optional[str]:
        """Deterministic rid-keyed routing over breaker-admissible
        replicas; falls back to ALL live replicas when every breaker
        is open (routing away from everyone is routing to no one)."""
        if not live:
            return None
        adm = [n for n in live if self._breaker(n).allows(now)]
        if not adm:
            adm = list(live)
        name = adm[int(rid) % len(adm)]
        self._breaker(name).note_probe()
        return name

    def admission_result(self, replica: str, ok: bool,
                         now: float = 0.0) -> None:
        """Feed one fresh-admission outcome to ``replica``'s breaker
        (sheds only — a malformed-request rejection says nothing about
        the replica's health and must be fed as neither)."""
        b = self._breaker(replica)
        before = b.state
        b.record(ok, now)
        if b.state != before:
            if b.state == "open":
                _monitor.inc("serving.failover.breaker.opened",
                             doc="circuit-breaker trips: a replica "
                                 "whose fresh admissions keep "
                                 "shedding routes new work away for "
                                 "a cooldown")
            elif b.state == "closed":
                _monitor.inc("serving.failover.breaker.closed",
                             doc="half-open probes that succeeded and "
                                 "closed the breaker")
            _trace.instant("serving.failover.breaker", replica=replica,
                           state=b.state)
            _forensics.decision("breaker", replica=replica,
                                state=b.state, failures=b.failures)
        _monitor.set_gauge(
            "serving.failover.breaker.open",
            sum(1 for x in self.breakers.values()
                if x.state != "closed"),
            doc="replicas currently open or half-open")

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict:
        by_state: Dict[str, int] = {}
        for rec in self.terminal.values():
            s = rec.get("state", "unknown")
            by_state[s] = by_state.get(s, 0) + 1
        return {"pending": len(self.pending),
                "inflight_redispatch": len(self._redispatched),
                "counters": dict(self.counters),
                "quarantined_hashes": len(self.quarantined_hashes),
                "terminal_by_state": by_state,
                "breakers": {n: b.as_dict()
                             for n, b in sorted(self.breakers.items())}}


# -- active-coordinator registry (the federation /fleet/serving block) ------

_ACTIVE_COORD = None


def set_active_coordinator(coord: Optional[FailoverCoordinator]) -> None:
    """Register the live coordinator for the monitor plane (weakref —
    the controller owns its lifetime, the HTTP surface must never
    extend it)."""
    global _ACTIVE_COORD
    _ACTIVE_COORD = None if coord is None else weakref.ref(coord)


def active_coordinator() -> Optional[FailoverCoordinator]:
    ref = _ACTIVE_COORD
    return ref() if ref is not None else None
