"""paddle.utils.unique_name parity (reference:
python/paddle/utils/unique_name.py): generate / guard / switch over a
per-context name counter."""
from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "guard", "switch"]


class _Generator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, key):
        n = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{n}"


_generator = _Generator()


def generate(key):
    return _generator(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)



def generate_with_ignorable_key(key):
    """reference: utils/unique_name.py generate_with_ignorable_key —
    generate() but the key is droppable under memory-optimized naming;
    naming here is always full, so it forwards."""
    return generate(key)
