"""paddle.utils.dlpack parity (reference:
python/paddle/utils/dlpack.py): zero-copy tensor interchange. JAX arrays
speak the DLPack protocol natively (`__dlpack__`), so torch/numpy/cupy
consumers interoperate directly."""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


class _Carrier:
    """Holds a DLPack capsule plus its device so consumers that require
    the full protocol (__dlpack__ AND __dlpack_device__) can ingest it.
    The capsule is single-use, like the reference's."""

    def __init__(self, capsule, device):
        self._capsule = capsule
        self._device = device

    def __dlpack__(self, stream=None, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return self._device


def to_dlpack(x):
    from ..ops._op import unwrap

    arr = unwrap(x)
    return _Carrier(arr.__dlpack__(), arr.__dlpack_device__())


def from_dlpack(dlpack):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if hasattr(dlpack, "__dlpack__"):
        return Tensor(jnp.from_dlpack(dlpack))
    # bare capsule (e.g. from torch.utils.dlpack.to_dlpack): assume host
    return Tensor(jnp.from_dlpack(_Carrier(dlpack, (1, 0))))
