
"""paddle.utils parity: deprecation decorator, version gate, install
check, lazy import (reference: python/paddle/utils/__init__.py), plus
the unique_name / dlpack / download submodules."""
from __future__ import annotations

import functools
import importlib
import warnings

from . import cpp_extension  # noqa: F401
from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import unique_name  # noqa: F401
from ..core import enforce as E

__all__ = ["deprecated", "require_version", "run_check", "try_import",
           "unique_name", "dlpack", "download", "cpp_extension"]


def deprecated(update_to="", since="", reason="", level=0):
    """Mark an API deprecated (reference: utils/deprecated.py): warns on
    call; level>=2 raises."""

    def decorator(fn):
        msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use {update_to} instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level >= 2:
                raise E.PreconditionNotMetError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        wrapper.__deprecated_message__ = msg
        return wrapper

    return decorator


def require_version(min_version, max_version=None):
    """Check the installed framework version against a range (reference:
    utils/__init__.py require_version)."""
    from .. import __version__

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")


def run_check():
    """Smoke-check the install (reference: utils/install_check.py
    run_check): run a tiny compiled matmul on the available device."""
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    out = jax.jit(lambda a, b: a @ b)(jnp.ones((2, 3)), jnp.ones((3, 2)))
    assert out.shape == (2, 2)
    print(f"paddle_tpu is installed successfully! device: "
          f"{d.platform}:{d.id} ({d.device_kind})")


def try_import(module_name, err_msg=None):
    """Import a module or raise a helpful error (reference:
    utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"Failed to import {module_name}. Install it to "
                       f"use this feature.") from e


# -- structure utilities (reference: utils/layers_utils.py; the reference
# binds them into paddle.utils via relative imports). jax.tree is the
# native engine for all of them. ------------------------------------------

def is_sequence(seq):
    """True for (possibly nested) non-string sequences/dicts
    (reference layers_utils.is_sequence)."""
    return isinstance(seq, dict) or (
        isinstance(seq, (list, tuple)) and not isinstance(seq, str))


def flatten(nest):
    """Flatten a nested structure to a list of leaves (reference
    layers_utils.flatten)."""
    import jax
    return jax.tree.leaves(nest,
                           is_leaf=lambda x: not is_sequence(x))


def pack_sequence_as(structure, flat_sequence):
    """Inverse of flatten (reference layers_utils.pack_sequence_as)."""
    import jax
    treedef = jax.tree.structure(
        structure, is_leaf=lambda x: not is_sequence(x))
    return jax.tree.unflatten(treedef, list(flat_sequence))


def map_structure(func, *structures):
    """Apply func leaf-wise, preserving structure (reference
    layers_utils.map_structure)."""
    import jax
    return jax.tree.map(func, *structures,
                        is_leaf=lambda x: not is_sequence(x))


def assert_same_structure(nest1, nest2, check_types=True):
    """Raise ValueError when the two nests differ in structure
    (reference layers_utils.assert_same_structure)."""
    import jax
    leaf = (lambda x: not is_sequence(x))
    s1 = jax.tree.structure(nest1, is_leaf=leaf)
    s2 = jax.tree.structure(nest2, is_leaf=leaf)
    if s1 != s2:
        raise E.InvalidArgumentError(
            f"The two structures don't match: {s1} vs {s2}")


def hold_mutable_vars(variables):
    """Context manager freezing a snapshot of mutable containers
    (reference layers_utils.hold_mutable_vars)."""
    import contextlib
    import copy

    @contextlib.contextmanager
    def _hold():
        saved = [copy.copy(v) for v in variables]
        try:
            yield
        finally:
            for v, s in zip(variables, saved):
                if isinstance(v, list):
                    v[:] = s
                elif isinstance(v, dict):
                    v.clear()
                    v.update(s)
    return _hold()


def copy_mutable_vars(structure):
    """Shallow-copy mutable containers inside a structure (reference
    layers_utils.copy_mutable_vars)."""
    import copy
    if isinstance(structure, (list, dict)):
        return copy.copy(structure)
    return structure


def convert_to_list(value, n, name, dtype=int):
    """Scalar-or-iterable -> list of length n (reference
    utils/__init__.py convert_to_list)."""
    if isinstance(value, dtype):
        return [value] * n
    try:
        value_list = list(value)
    except TypeError:
        raise E.InvalidArgumentError(
            f"{name} must be a {dtype.__name__} or iterable, got {value!r}")
    if len(value_list) != n:
        raise E.InvalidArgumentError(
            f"{name} must have {n} elements, got {len(value_list)}")
    return value_list


def convert_shape_to_list(shape):
    """Shape (tuple/list/Tensor elements) -> plain int list (reference
    utils/__init__.py convert_shape_to_list)."""
    import numpy as np
    out = []
    for s in shape:
        if hasattr(s, "_data"):
            out.append(int(np.asarray(s._data)))
        else:
            out.append(int(s))
    return out


def get_int_tensor_list(ele_list):
    """List of scalars/0-d tensors -> list of ints (reference
    get_int_tensor_list, simplified for the eager path)."""
    return convert_shape_to_list(ele_list)


def to_sequence(nest):
    """Wrap non-sequences into a single-element list (reference
    layers_utils.to_sequence)."""
    if is_sequence(nest):
        return nest
    return [nest]
